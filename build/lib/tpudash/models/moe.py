"""Expert parallelism: switch-style top-1 MoE over an ``ep`` mesh axis.

Completes the parallelism set (dp/tp in workload.py, sp in
ring_attention.py, pp in pipeline.py): experts are sharded across the
``ep`` axis and tokens travel to their expert and back with two
``lax.all_to_all`` collectives — the all-to-all traffic pattern the
dashboard's ICI panels are built to surface.  The reference has no model
code at all (SURVEY.md §5), so like its siblings this is workload-side
machinery the rebuild adds.

TPU-first construction:
- dispatch is the dense einsum formulation (tokens → one-hot dispatch
  tensor → ``[experts, capacity, d_model]`` buffers): every shape is
  static, routing is matmuls the MXU executes, and there is no gather /
  scatter with data-dependent shapes that would defeat XLA;
- the ``ep`` axis doubles as the token-group axis (each device routes its
  own S tokens), so the exchange is one all_to_all out and one back, both
  riding ICI on a real slice;
- top-1 (switch) routing with a static capacity ``C = ceil(S/E · cf)``;
  overflowed tokens are dropped from the expert path (standard switch
  behavior) and the auxiliary load-balancing loss pushes the router
  toward uniform expert load;
- everything differentiates: the straight-through gate multiplies the
  combine weights, and all_to_all's transpose is all_to_all.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudash.models.ring_attention import _SHARD_MAP_KW, shard_map


@dataclass(frozen=True)
class MoEConfig:
    vocab: int = 128
    d_model: int = 64
    d_ff: int = 128
    n_experts: int = 8
    seq: int = 16
    batch: int = 8
    #: experts per token: 1 = switch routing, 2 = Mixtral-style top-2
    #: (gates renormalized over the chosen experts).
    top_k: int = 1
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01
    lr: float = 3e-4


def init_moe_params(key: jax.Array, cfg: MoEConfig) -> dict:
    """Expert-stacked params: FFN weights carry a leading n_experts dim
    (sharded over ep); embed/router/unembed are replicated."""
    k_embed, k_router, k_up, k_down, k_out = jax.random.split(key, 5)
    d, f, E = cfg.d_model, cfg.d_ff, cfg.n_experts

    def norm(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(
            jnp.bfloat16
        )

    return {
        "embed": norm(k_embed, (cfg.vocab, d), 0.02),
        "router": (jax.random.normal(k_router, (d, E), jnp.float32) * d**-0.5),
        "w_up": norm(k_up, (E, d, f), d**-0.5),
        "w_down": norm(k_down, (E, f, d), f**-0.5),
        "ln": jnp.ones((d,), jnp.float32),
        "unembed": norm(k_out, (d, cfg.vocab), d**-0.5),
    }


def moe_param_specs() -> dict:
    return {
        "embed": P(),
        "router": P(),
        "w_up": P("ep"),
        "w_down": P("ep"),
        "ln": P(),
        "unembed": P(),
    }


def _capacity(tokens_per_group: int, cfg: MoEConfig) -> int:
    # K·S assignments spread over E experts (GShard convention): without
    # the top_k factor, top-2 at cf=1.25 would drop ~37% of assignments
    # even under perfectly balanced load
    return max(
        1,
        math.ceil(
            cfg.top_k * tokens_per_group / cfg.n_experts * cfg.capacity_factor
        ),
    )


def _route(x: jax.Array, router: jax.Array, cfg: MoEConfig, capacity: int):
    """Top-k routing for local tokens x (S, d) → (dispatch (S,E,C),
    combine (S,E,C), aux-loss scalar).

    k=1 is switch routing; k=2 is Mixtral-style with gates renormalized
    over the chosen experts.  Capacity positions are assigned choice-rank
    first (all primary assignments, then secondary), the standard
    mesh-tensorflow ordering, so a full expert drops secondary traffic
    before primary."""
    E, K = cfg.n_experts, cfg.top_k
    logits = jnp.einsum("sd,de->se", x.astype(jnp.float32), router)
    probs = jax.nn.softmax(logits, axis=-1)
    top_gates, top_idx = jax.lax.top_k(probs, K)  # (S, K)
    if K > 1:  # Mixtral renormalizes over chosen experts; switch (K=1)
        # keeps the raw top-1 probability as the gate
        top_gates = top_gates / jnp.sum(top_gates, axis=-1, keepdims=True)

    dispatch = jnp.zeros((x.shape[0], E, capacity), jnp.float32)
    combine = jnp.zeros_like(dispatch)
    usage = jnp.zeros((E,), jnp.float32)  # slots taken per expert so far
    frac = jnp.zeros((E,), jnp.float32)
    for j in range(K):  # static, tiny (K ≤ 2)
        mask = jax.nn.one_hot(top_idx[:, j], E, dtype=jnp.float32)  # (S, E)
        pos = jnp.cumsum(mask, axis=0) * mask - mask + usage[None, :] * mask
        keep = mask * (pos < capacity)
        d_j = keep[..., None] * jax.nn.one_hot(
            pos.astype(jnp.int32), capacity, dtype=jnp.float32
        )
        dispatch = dispatch + d_j
        combine = combine + d_j * top_gates[:, j, None, None]
        usage = usage + jnp.sum(keep, axis=0)
        frac = frac + jnp.mean(mask, axis=0)
    # load-balance aux: E · Σ_e (assigned fraction_e / K · mean prob_e)
    mean_prob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac / K * mean_prob)
    return dispatch, combine, aux


def moe_ffn_local(x: jax.Array, params: dict, cfg: MoEConfig, n_groups: int):
    """Per-shard switch FFN (runs inside shard_map over ``ep``).

    x: (S, d) local tokens; params["w_up"/"w_down"] hold this shard's
    E/n_groups experts.  Returns ((S, d) output, aux loss).
    """
    S, d = x.shape
    E, G = cfg.n_experts, n_groups
    EL = E // G
    C = _capacity(S, cfg)
    dispatch, combine, aux = _route(x, params["router"], cfg, C)

    # (S,E,C) × (S,d) → expert-major send buffer, dim0 = owning shard
    sent = jnp.einsum(
        "sec,sd->ecd", dispatch, x.astype(jnp.float32)
    ).reshape(G, EL, C, d)
    # exchange: recv[src, el] = source src's tokens for local expert el
    recv = lax.all_to_all(sent, "ep", split_axis=0, concat_axis=0, tiled=False)
    h = jnp.einsum(
        "gecd,edf->gecf",
        recv.astype(jnp.bfloat16),
        params["w_up"],
        preferred_element_type=jnp.bfloat16,
    )
    h = jax.nn.gelu(h)
    # f32 operands for the down-projection: bf16×bf16→f32 dots hit an
    # unimplemented CPU thunk for this batched layout (TPU is fine either
    # way — XLA re-fuses), and f32 accumulation is what we want anyway
    out = jnp.einsum(
        "gecf,efd->gecd",
        h.astype(jnp.float32),
        params["w_down"].astype(jnp.float32),
    )
    # return trip: back[e_global, :, :] = this shard's tokens, all experts
    back = lax.all_to_all(out, "ep", split_axis=0, concat_axis=0, tiled=False)
    y = jnp.einsum("sec,ecd->sd", combine, back.reshape(E, C, d))
    return y.astype(x.dtype), aux


def _moe_forward_local(params: dict, tokens: jax.Array, cfg: MoEConfig, G: int):
    """Embed → residual MoE block → unembed, on one ep shard's tokens."""
    B, T = tokens.shape
    x = params["embed"][tokens].astype(jnp.bfloat16).reshape(B * T, cfg.d_model)
    x32 = x.astype(jnp.float32)
    normed = (
        x32
        * lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
        * params["ln"]
    ).astype(jnp.bfloat16)
    y, aux = moe_ffn_local(normed, params, cfg, G)
    h = x + y.astype(jnp.bfloat16)
    logits = jnp.einsum(
        "sd,dv->sv", h, params["unembed"], preferred_element_type=jnp.float32
    )
    return logits.reshape(B, T, cfg.vocab), aux


def make_moe_loss(mesh: Mesh, cfg: MoEConfig):
    """loss(params, tokens) with tokens sharded over ``ep`` (each shard is
    one routing group) and experts sharded over ``ep``."""
    G = mesh.shape["ep"]
    if cfg.n_experts % G:
        raise ValueError(f"n_experts={cfg.n_experts} not divisible by ep={G}")

    def body(params, tokens):
        logits, aux = _moe_forward_local(params, tokens[:, :-1], cfg, G)
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        loss = -jnp.mean(ll) + cfg.router_aux_weight * aux
        return lax.pmean(loss, "ep")

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(moe_param_specs(), P("ep", None)),
        out_specs=P(),
        **_SHARD_MAP_KW,
    )


def make_moe_train_step(mesh: Mesh, cfg: MoEConfig):
    """jit the expert-parallel train step; returns (step_fn, shard_inputs)
    like the tp/ring/pipeline siblings."""
    loss_fn = make_moe_loss(mesh, cfg)
    p_shard = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        moe_param_specs(),
        is_leaf=lambda x: isinstance(x, P),
    )
    token_shard = NamedSharding(mesh, P("ep", None))
    opt = optax.adamw(cfg.lr, weight_decay=0.01)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(
        train_step,
        in_shardings=(p_shard, None, token_shard),
        out_shardings=(p_shard, None, None),
        donate_argnums=(0, 1),
    )

    def shard_inputs(params, opt_state, tokens):
        params = jax.device_put(params, p_shard)
        tokens = jax.device_put(tokens, token_shard)
        return params, opt_state, tokens

    return step, shard_inputs


def make_moe_train_state(key: jax.Array, cfg: MoEConfig):
    params = init_moe_params(key, cfg)
    opt_state = optax.adamw(cfg.lr, weight_decay=0.01).init(params)
    return params, opt_state


# --- correctness oracle ------------------------------------------------------

def dense_moe_reference(x: jax.Array, params: dict, cfg: MoEConfig) -> jax.Array:
    """Per-token oracle: y[s] = Σ_j gate_j[s] · FFN_{expert_j(s)}(x[s]),
    no capacity drops.  Matches moe_ffn_local exactly when capacity ≥ the
    largest per-expert token count (tests use capacity_factor=n_experts)."""
    logits = jnp.einsum("sd,de->se", x.astype(jnp.float32), params["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    top_gates, top_idx = jax.lax.top_k(probs, cfg.top_k)
    if cfg.top_k > 1:
        top_gates = top_gates / jnp.sum(top_gates, axis=-1, keepdims=True)
    y = jnp.zeros((x.shape[0], cfg.d_model), jnp.float32)
    for j in range(cfg.top_k):
        expert = top_idx[:, j]
        w_up = params["w_up"][expert]  # (S, d, f)
        w_down = params["w_down"][expert]
        h = jnp.einsum(
            "sd,sdf->sf", x.astype(jnp.bfloat16), w_up,
            preferred_element_type=jnp.bfloat16,
        )
        h = jax.nn.gelu(h)
        yj = jnp.einsum(
            "sf,sfd->sd", h, w_down, preferred_element_type=jnp.float32
        )
        y = y + top_gates[:, j, None] * yj
    return y.astype(x.dtype)
