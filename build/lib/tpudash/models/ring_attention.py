"""Ring attention: sequence/context parallelism over an ``sp`` mesh axis.

The reference has no model code at all (SURVEY.md §5 "long-context /
sequence parallelism: not applicable" — it is a dashboard), so this module
is part of tpudash's *workload* side: a long-context demo workload whose
ICI traffic (the rotating K/V blocks) lights up the dashboard's
``tpu_ici_*`` series, and a reusable TPU-native ring-attention primitive.

TPU-first construction:
- activations are sequence-sharded ``P(dp, sp)``; each device holds a
  contiguous (B, T/sp) block of Q, K and V;
- K/V blocks rotate around the ``sp`` ring with ``lax.ppermute`` — a
  neighbor-to-neighbor transfer that maps onto ICI links (no all-gather of
  the full sequence, so HBM stays O(T/sp) per chip);
- softmax is streamed flash-style (running max / running sum / f32
  accumulator), so no device ever materializes a T×T score matrix;
- the ring is a ``lax.scan`` with a static trip count (the mesh axis
  size), so the whole loop is one compiled body and reverse-mode
  differentiation works (the transpose of ppermute is the reverse
  ppermute);
- causal masking is by *global* positions, reconstructed from
  ``lax.axis_index`` and the rotation step — block (i) arriving at device
  (d) came from device (d - i) mod sp.

Simplification kept deliberately: causally dead blocks are still computed
and masked rather than skipped (skipping needs a data-dependent ring
schedule; at demo scale masking costs <2× and keeps the loop body static).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# check_vma/check_rep off: the ring body mixes ppermute-varying and locally
# created arrays in one scan carry, which the replication/vma checker rejects
try:
    from jax import shard_map  # jax >= 0.8

    _SHARD_MAP_KW: dict = {"check_vma": False}
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

    _SHARD_MAP_KW = {"check_rep": False}

_NEG_BIG = -1e30  # finite "-inf": keeps exp() well-defined before the first
                  # unmasked key (the own-block step) establishes a real max


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool,
) -> jax.Array:
    """Per-shard body (runs inside shard_map).

    q/k/v: (B, T_local, H, hd) — this device's sequence block.
    Returns (B, T_local, H, hd) attention output for the local queries.
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, T, H, hd = q.shape
    scale = hd**-0.5

    # head-major layout for the MXU-friendly (Tq, Tk) score matmuls
    qh = q.transpose(0, 2, 1, 3).astype(jnp.float32) * scale  # B,H,Tq,hd

    m0 = jnp.full((B, H, T), _NEG_BIG, jnp.float32)      # running max
    l0 = jnp.zeros((B, H, T), jnp.float32)               # running denom
    acc0 = jnp.zeros((B, H, T, hd), jnp.float32)         # running numerator

    q_pos = my_idx * T + lax.broadcasted_iota(jnp.int32, (T, T), 0)

    def step(carry, i):
        k_blk, v_blk, m, l, acc = carry
        kh = k_blk.transpose(0, 2, 1, 3).astype(jnp.float32)
        vh = v_blk.transpose(0, 2, 1, 3).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh)  # f32 scores
        if causal:
            src = (my_idx - i) % axis_size  # origin shard of this K/V block
            k_pos = src * T + lax.broadcasted_iota(jnp.int32, (T, T), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_BIG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vh)
        # rotate K/V one hop around the ring (device j's block → j+1)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m_new, l, acc), None

    (_, _, _, l, acc), _ = lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(axis_size)
    )
    out = acc / l[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    dp_axis: str = "dp",
    sp_axis: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Ring attention over sequence-sharded q/k/v of shape (B, T, H, hd).

    B is sharded over ``dp_axis``, T over ``sp_axis``; heads/head_dim stay
    local.  Callable under jit; XLA lowers the internal ppermutes onto ICI
    neighbor links on a real slice.
    """
    spec = P(dp_axis, sp_axis, None, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local, axis_name=sp_axis, causal=causal
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **_SHARD_MAP_KW,
    )
    return fn(q, k, v)


def reference_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True
) -> jax.Array:
    """Unsharded softmax attention — the correctness oracle for tests."""
    B, T, H, hd = q.shape
    qh = q.transpose(0, 2, 1, 3).astype(jnp.float32)
    kh = k.transpose(0, 2, 1, 3).astype(jnp.float32)
    vh = v.transpose(0, 2, 1, 3).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * (hd**-0.5)
    if causal:
        rows = lax.broadcasted_iota(jnp.int32, (T, T), 0)
        cols = lax.broadcasted_iota(jnp.int32, (T, T), 1)
        s = jnp.where(cols <= rows, s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


# --- long-context demo workload: sequence-parallel transformer --------------

def make_ring_train_step(mesh: Mesh, cfg):
    """Training step for the demo transformer with ring attention over
    ``sp`` and batch over ``dp`` (long-context shape: T split across the
    mesh, so per-chip activation memory is O(T/sp)).

    Params are replicated (this workload exercises the sequence axis; see
    workload.make_sharded_train_step for the tp-sharded variant).  Returns
    (step_fn, shard_inputs) like its tp sibling.
    """
    import optax

    from tpudash.models import workload as w

    token_shard = NamedSharding(mesh, P("dp", None))
    replicated = NamedSharding(mesh, P())

    def attention_ring(x, wqkv, wo):
        B, T, d = x.shape
        H, hd = cfg.n_heads, cfg.head_dim
        qkv = jnp.einsum(
            "btd,de->bte", x, wqkv, preferred_element_type=jnp.bfloat16
        )
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, hd)
        k = k.reshape(B, T, H, hd)
        v = v.reshape(B, T, H, hd)
        out = ring_attention(q, k, v, mesh).reshape(B, T, d)
        return jnp.einsum(
            "btd,de->bte", out, wo, preferred_element_type=jnp.bfloat16
        )

    def forward(params, tokens):
        x = params["embed"][tokens].astype(jnp.bfloat16)
        # keep activations sequence-sharded between layers; XLA keeps the
        # per-token matmuls local and only the ring communicates
        x = lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("dp", "sp", None))
        )

        def block(h, layer):
            h = h + attention_ring(
                w._rmsnorm(h, layer["ln1"]), layer["wqkv"], layer["wo"]
            )
            h = h + w._mlp(
                w._rmsnorm(h, layer["ln2"]), layer["w_up"], layer["w_down"]
            )
            return h, None

        x, _ = lax.scan(jax.checkpoint(block), x, params["blocks"])
        x = w._rmsnorm(x, params["ln_f"])
        return jnp.einsum(
            "btd,dv->btv", x, params["unembed"],
            preferred_element_type=jnp.float32,
        )

    def loss_fn(params, tokens):
        # run the forward on the FULL sequence (T must stay divisible by the
        # sp axis for the P(dp, sp) activation sharding) and drop the final
        # position from the logits instead of from the input
        logits = forward(params, tokens)[:, :-1]
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    opt = w.make_optimizer(cfg)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(
        train_step,
        in_shardings=(replicated, None, token_shard),
        out_shardings=(replicated, None, None),
        donate_argnums=(0, 1),
    )

    def shard_inputs(params, opt_state, tokens):
        params = jax.device_put(params, replicated)
        tokens = jax.device_put(tokens, token_shard)
        return params, opt_state, tokens

    return step, shard_inputs
