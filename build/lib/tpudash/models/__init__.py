"""Demo workload models.

A monitoring stack needs something to monitor: this package provides a
small, honest TPU workload — a decoder-only transformer LM trained with a
dp×tp-sharded train step over a jax Mesh — used to (a) generate real
TensorCore/HBM/ICI activity for live-dashboard demos and probe calibration,
and (b) back the driver's compile/dry-run entry points.  The reference has
no model code at all (SURVEY.md §5 "long-context: not applicable"); this is
the TPU-native analogue of the GPU burn-in jobs its users would monitor.
"""

from tpudash.models.workload import (  # noqa: F401
    WorkloadConfig,
    forward,
    init_params,
    loss_fn,
    make_sharded_train_step,
    make_train_state,
    param_shardings,
)
