"""Checkpoint/resume for the demo workload (orbax).

The reference has no checkpoint/resume of any kind — its only persisted
state is in-session Streamlit widget state, lost on refresh (SURVEY.md §5
"Checkpoint / resume: none").  The rebuild's UI state already persists
(app/state.py); this module adds the *training* side: the background
workload saves ``{params, opt_state, step}`` with orbax every N steps and
resumes from the latest step after a restart, so the dashboard's loss /
steps counters continue instead of restarting from scratch.

Design notes (TPU-first):
- arrays are pulled to host (``jax.device_get``) before save: on a sharded
  mesh the gather rides ICI once per checkpoint interval, and the on-disk
  tree is topology-independent — a checkpoint taken on an 8-chip mesh
  restores onto 1 chip or 32 (resharding happens at ``device_put`` via the
  runner's shard_inputs);
- restore goes through an ``item=`` template built from a fresh
  ``make_train_state`` so optax's NamedTuple structure round-trips exactly;
- steps are directories ``step_<n>``; writes are atomic (orbax writes to a
  tmp dir and renames), retention keeps the newest ``keep`` steps.
"""

from __future__ import annotations

import logging
import os
import re
import shutil

import jax

log = logging.getLogger(__name__)

_STEP_RE = re.compile(r"^step_(\d+)$")


class WorkloadCheckpointer:
    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = max(1, keep)
        os.makedirs(directory, exist_ok=True)
        import orbax.checkpoint as ocp

        self._ckptr = ocp.PyTreeCheckpointer()

    # -- step bookkeeping ----------------------------------------------------
    def steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> "int | None":
        steps = self.steps()
        return steps[-1] if steps else None

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    # -- save / restore ------------------------------------------------------
    def save(self, step: int, params, opt_state) -> None:
        """Checkpoint the train state at ``step``; prunes old steps."""
        tree = {
            "step": step,
            "params": jax.device_get(params),
            "opt_state": jax.device_get(opt_state),
        }
        path = self._path(step)
        if os.path.exists(path):  # same-step re-save (e.g. final save on stop)
            shutil.rmtree(path)
        self._ckptr.save(path, tree)
        for old in self.steps()[: -self.keep]:
            shutil.rmtree(self._path(old), ignore_errors=True)
        log.info("checkpointed workload at step %d → %s", step, path)

    def restore_latest(self, template_params, template_opt_state):
        """Return (params, opt_state, step) from the newest checkpoint, or
        None when the directory holds none.  Templates define the pytree
        structure (fresh ``make_train_state`` output works)."""
        step = self.latest_step()
        if step is None:
            return None
        tmpl = {
            "step": 0,
            "params": jax.device_get(template_params),
            "opt_state": jax.device_get(template_opt_state),
        }
        tree = self._ckptr.restore(self._path(step), item=tmpl)
        log.info("restored workload checkpoint step %d", tree["step"])
        return tree["params"], tree["opt_state"], int(tree["step"])
