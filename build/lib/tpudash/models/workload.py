"""Decoder-only transformer demo workload, sharded dp×tp.

TPU-first construction:
- layers are stacked and scanned (``lax.scan``) so XLA compiles ONE layer
  body regardless of depth — no Python-loop unrolling, fast compiles;
- attention/MLP matmuls run in bf16 with f32 accumulation
  (``preferred_element_type``) — MXU-native;
- sharding is declarative: ``param_shardings`` gives Megatron-style
  column/row-parallel PartitionSpecs over the ``tp`` axis and batch over
  ``dp``; XLA's sharding propagation inserts the psum/all-gather
  collectives, which ride ICI on a real slice;
- static shapes throughout; the causal mask is built with broadcasted_iota
  (no dynamic slicing).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class WorkloadConfig:
    vocab: int = 512
    d_model: int = 256
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 1024
    seq: int = 128
    batch: int = 8
    lr: float = 3e-4

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


# --- parameters -------------------------------------------------------------

def init_params(key: jax.Array, cfg: WorkloadConfig) -> dict:
    """Stacked-layer param pytree (leading dim = n_layers on block leaves)."""
    k_embed, k_layers, k_out = jax.random.split(key, 3)
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layers

    def norm(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(
            jnp.bfloat16
        )

    ks = jax.random.split(k_layers, 6)
    return {
        "embed": norm(k_embed, (cfg.vocab, d), 0.02),
        "blocks": {
            "ln1": jnp.ones((L, d), jnp.float32),
            "wqkv": norm(ks[0], (L, d, 3 * d), d**-0.5),
            "wo": norm(ks[1], (L, d, d), d**-0.5),
            "ln2": jnp.ones((L, d), jnp.float32),
            "w_up": norm(ks[2], (L, d, f), d**-0.5),
            "w_down": norm(ks[3], (L, f, d), f**-0.5),
        },
        "ln_f": jnp.ones((d,), jnp.float32),
        "unembed": norm(k_out, (d, cfg.vocab), d**-0.5),
    }


def param_shardings(mesh: Mesh) -> dict:
    """Megatron-style tp shardings: qkv/up column-parallel (output dim on
    tp), o/down row-parallel (input dim on tp); embeddings sharded on the
    model dim; norms replicated.  Leading layer-stack dim never sharded."""

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    return {
        "embed": ns(None, "tp"),
        "blocks": {
            "ln1": ns(None, None),
            "wqkv": ns(None, None, "tp"),
            "wo": ns(None, "tp", None),
            "ln2": ns(None, None),
            "w_up": ns(None, None, "tp"),
            "w_down": ns(None, "tp", None),
        },
        "ln_f": ns(None),
        "unembed": ns(None, "tp"),
    }


# --- forward ----------------------------------------------------------------

def _rmsnorm(x: jax.Array, scale: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)
    rms = lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (x32 * rms * scale).astype(jnp.bfloat16)


def _sdpa(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Causal scaled-dot-product attention on head-major (B, H, T, hd)
    tensors — the core shared by the fused-qkv serial path and the
    tp-sharded 3D pipeline (models/pipeline.py), so the mask/dtype points
    cannot diverge between them."""
    T, hd = q.shape[2], q.shape[3]
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * (hd**-0.5)
    rows = lax.broadcasted_iota(jnp.int32, (T, T), 0)
    cols = lax.broadcasted_iota(jnp.int32, (T, T), 1)
    scores = jnp.where(cols <= rows, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(jnp.bfloat16)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", probs, v, preferred_element_type=jnp.bfloat16
    )


def _attention(x: jax.Array, wqkv: jax.Array, wo: jax.Array, cfg: WorkloadConfig) -> jax.Array:
    B, T, d = x.shape
    H, hd = cfg.n_heads, cfg.head_dim
    qkv = jnp.einsum("btd,de->bte", x, wqkv, preferred_element_type=jnp.bfloat16)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    out = _sdpa(q, k, v)
    out = out.transpose(0, 2, 1, 3).reshape(B, T, d)
    return jnp.einsum("btd,de->bte", out, wo, preferred_element_type=jnp.bfloat16)


def _mlp(x: jax.Array, w_up: jax.Array, w_down: jax.Array) -> jax.Array:
    h = jnp.einsum("btd,df->btf", x, w_up, preferred_element_type=jnp.bfloat16)
    h = jax.nn.gelu(h)
    return jnp.einsum("btf,fd->btd", h, w_down, preferred_element_type=jnp.bfloat16)


def forward(params: dict, tokens: jax.Array, cfg: WorkloadConfig) -> jax.Array:
    """tokens (B, T) int32 → logits (B, T, vocab) f32."""
    x = params["embed"][tokens].astype(jnp.bfloat16)

    def block(carry, layer):
        h = carry
        h = h + _attention(_rmsnorm(h, layer["ln1"]), layer["wqkv"], layer["wo"], cfg)
        h = h + _mlp(_rmsnorm(h, layer["ln2"]), layer["w_up"], layer["w_down"])
        return h, None

    # remat each layer: without it, scan saves every layer's T×T attention
    # probabilities for backward (O(L·B·H·T²) HBM — OOMs a 16 GiB chip at
    # modest sizes); recomputing them trades ~1/3 more FLOPs for O(1)-layer
    # activation memory
    x, _ = lax.scan(jax.checkpoint(block), x, params["blocks"])
    x = _rmsnorm(x, params["ln_f"])
    return jnp.einsum(
        "btd,dv->btv", x, params["unembed"], preferred_element_type=jnp.float32
    )


def loss_fn(params: dict, tokens: jax.Array, cfg: WorkloadConfig) -> jax.Array:
    """Next-token cross-entropy (shift-by-one inside the batch)."""
    logits = forward(params, tokens[:, :-1], cfg)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


# --- training ---------------------------------------------------------------

def make_optimizer(cfg: WorkloadConfig):
    return optax.adamw(cfg.lr, weight_decay=0.01)


def make_train_state(key: jax.Array, cfg: WorkloadConfig):
    params = init_params(key, cfg)
    opt_state = make_optimizer(cfg).init(params)
    return params, opt_state


def train_step(params, opt_state, tokens, cfg: WorkloadConfig):
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
    updates, opt_state = make_optimizer(cfg).update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


def make_sharded_train_step(mesh: Mesh, cfg: WorkloadConfig):
    """jit the FULL train step over the mesh: params tp-sharded, batch
    dp-sharded, optimizer state sharded like params.  XLA propagates the
    shardings through grads/updates and inserts the tp psums + dp gradient
    all-reduce.  Returns (step_fn, shard_inputs)."""
    p_shard = param_shardings(mesh)
    batch_shard = NamedSharding(mesh, P("dp", None))

    # opt_state shardings are left to propagation (None): adamw's mu/nu
    # mirror the param tree, and XLA shards them like the params they track.
    step = jax.jit(
        lambda p, o, t: train_step(p, o, t, cfg),
        in_shardings=(p_shard, None, batch_shard),
        out_shardings=(p_shard, None, None),
        donate_argnums=(0, 1),
    )

    def shard_inputs(params, opt_state, tokens):
        params = jax.device_put(params, p_shard)
        tokens = jax.device_put(tokens, batch_shard)
        return params, opt_state, tokens

    return step, shard_inputs


def flops_per_step(cfg: WorkloadConfig) -> float:
    """Approximate training FLOPs per step (fwd+bwd ≈ 3× fwd matmul FLOPs)."""
    T, d, f, L, B = cfg.seq, cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.batch
    attn = 2 * T * d * 3 * d + 2 * T * T * d * 2 + 2 * T * d * d
    mlp = 2 * T * d * f * 2
    per_layer = attn + mlp
    fwd = B * (L * per_layer + 2 * T * d * cfg.vocab)
    return 3.0 * fwd
