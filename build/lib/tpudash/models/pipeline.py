"""Pipeline parallelism: GPipe-style microbatching over a ``pp`` mesh axis.

Like ring_attention, this is workload-side machinery the reference (a
dashboard with no model code, SURVEY.md §5) never had: a demo training
path whose stage-to-stage activation transfers ride ICI neighbor links,
completing the dp/tp/sp/pp/ep parallelism set.

TPU-first construction:
- the layer stack (leading dim L) is sharded over ``pp`` via shard_map
  in_specs, so stage s holds layers [s·L/P, (s+1)·L/P) — the same stacked
  pytree the dp×tp and ring workloads use, no per-stage param surgery;
- the schedule is a single ``lax.scan`` over M + P - 1 ticks (M
  microbatches, P stages): each tick every stage runs its layer block on
  its current microbatch and hands the activation to stage s+1 with
  ``lax.ppermute`` — neighbor traffic only, no all-gathers;
- the scan body is static (microbatch selection via ``jnp.where`` on
  ``lax.axis_index``), so XLA compiles ONE tick regardless of M and P and
  reverse-mode AD works through the whole schedule (the transpose of
  ppermute is the reverse ppermute — backward pipeline flows stage P-1 → 0
  automatically);
- the pipeline bubble is the standard (P-1)/(M+P-1) fraction; raising the
  microbatch count M amortizes it exactly as in GPipe.

Numerically the pipeline computes the SAME function as the serial
workload.forward — layers in stack order, identical kernels — which the
tests pin (pipeline loss == serial loss to f32 tolerance).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpudash.models import workload as w
from tpudash.models.ring_attention import _SHARD_MAP_KW, shard_map


def _stage_param_specs() -> dict:
    """PartitionSpecs for shard_map: layer stack sharded over pp, the rest
    replicated (embed/unembed run redundantly on every stage — cheap at
    demo scale and keeps every rank's program identical)."""
    blk = P("pp")  # shard dim 0 (the L layer-stack dim); rest replicated
    return {
        "embed": P(),
        "blocks": {k: blk for k in ("ln1", "wqkv", "wo", "ln2", "w_up", "w_down")},
        "ln_f": P(),
        "unembed": P(),
    }


def _stage_shardings(mesh: Mesh) -> dict:
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        _stage_param_specs(),
        is_leaf=lambda x: isinstance(x, P),
    )


def make_pipeline_loss(mesh: Mesh, cfg, num_microbatches: int):
    """Return loss(params, tokens) running the demo transformer as a
    P-stage pipeline over mesh axis ``pp`` with batch over ``dp``."""
    P_axis = mesh.shape["pp"]
    M = num_microbatches
    if cfg.n_layers % P_axis:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={P_axis}")

    def local_layers(x, blocks):
        def block(h, layer):
            h = h + w._attention(
                w._rmsnorm(h, layer["ln1"]), layer["wqkv"], layer["wo"], cfg
            )
            h = h + w._mlp(
                w._rmsnorm(h, layer["ln2"]), layer["w_up"], layer["w_down"]
            )
            return h, None

        x, _ = lax.scan(jax.checkpoint(block), x, blocks)
        return x

    def pipeline_body(params, tokens):
        # tokens: (B_local, T) — this dp shard's batch
        stage = lax.axis_index("pp")
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        B, Tm = inputs.shape
        if B % M:
            raise ValueError(f"local batch {B} not divisible by microbatches {M}")
        mb = B // M

        x = params["embed"][inputs].astype(jnp.bfloat16)  # every stage embeds
        x_mb = x.reshape(M, mb, Tm, cfg.d_model)
        # M real microbatches + P-1 drain ticks of zeros
        xs = jnp.concatenate(
            [x_mb, jnp.zeros((P_axis - 1, mb, Tm, cfg.d_model), x.dtype)]
        )

        def tick(recv, xt):
            # stage 0 pulls the next microbatch; later stages consume what
            # stage s-1 sent last tick (= microbatch t - s, the GPipe skew)
            inp = jnp.where(stage == 0, xt, recv)
            out = local_layers(inp, params["blocks"])
            send = lax.ppermute(
                out, "pp", [(j, (j + 1) % P_axis) for j in range(P_axis)]
            )
            return send, out

        _, outs = lax.scan(tick, jnp.zeros_like(xs[0]), xs)
        # on the LAST stage, tick t ≥ P-1 emits fully-processed microbatch
        # t-(P-1); earlier stages' outs are intermediate and unused here
        ys = outs[P_axis - 1 :]  # (M, mb, Tm, d)

        h = w._rmsnorm(ys, params["ln_f"])
        logits = jnp.einsum(
            "mbtd,dv->mbtv", h, params["unembed"],
            preferred_element_type=jnp.float32,
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        t_mb = targets.reshape(M, mb, Tm)
        ll = jnp.take_along_axis(logp, t_mb[..., None], axis=-1)[..., 0]
        local_loss = -jnp.mean(ll)
        # only the last stage computed real logits; everyone else masks to 0
        # and the psum replicates the value across the pp ring
        loss = lax.psum(
            jnp.where(stage == P_axis - 1, local_loss, 0.0), "pp"
        )
        return lax.pmean(loss, "dp")  # mean over dp shards

    fn = shard_map(
        pipeline_body,
        mesh=mesh,
        in_specs=(_stage_param_specs(), P("dp", None)),
        out_specs=P(),
        **_SHARD_MAP_KW,
    )
    return fn


# --- 3D parallelism: dp × pp × tp -------------------------------------------
#
# GPipe stages over ``pp`` with Megatron tensor parallelism inside each
# stage over ``tp`` (column-parallel q/k/v/up projections, row-parallel
# o/down projections, one psum per sublayer riding ICI), batch over ``dp``.
# Inside shard_map the tp collectives are written explicitly — the same
# math XLA's sharding propagation inserts for the jit-based dp×tp workload
# (workload.make_sharded_train_step), here composed with the pipeline's
# ppermute schedule in one program.
#
# The qkv projection is stored as separate wq/wk/wv (L, d, d) so the tp
# shard boundary falls on whole heads (a tp-split of the fused (d, 3d)
# wqkv would cut across the q|k|v concatenation); convert_params_3d maps
# the serial workload tree onto this layout for oracle comparisons.


def convert_params_3d(params: dict) -> dict:
    """Serial workload tree → 3D layout (fused wqkv split into wq/wk/wv)."""
    blocks = dict(params["blocks"])
    wqkv = blocks.pop("wqkv")
    d = wqkv.shape[1]
    blocks["wq"] = wqkv[:, :, :d]
    blocks["wk"] = wqkv[:, :, d : 2 * d]
    blocks["wv"] = wqkv[:, :, 2 * d :]
    return {**params, "blocks": blocks}


def _stage_param_specs_3d() -> dict:
    col = P("pp", None, "tp")  # column-parallel: output dim sharded
    row = P("pp", "tp", None)  # row-parallel: input dim sharded
    return {
        "embed": P(),
        "blocks": {
            "ln1": P("pp"),
            "wq": col,
            "wk": col,
            "wv": col,
            "wo": row,
            "ln2": P("pp"),
            "w_up": col,
            "w_down": row,
        },
        "ln_f": P(),
        "unembed": P(),
    }


def make_pipeline3d_loss(mesh: Mesh, cfg, num_microbatches: int):
    """loss(params3d, tokens) over mesh axes ("dp", "pp", "tp")."""
    P_axis, T_axis = mesh.shape["pp"], mesh.shape["tp"]
    M = num_microbatches
    if cfg.n_layers % P_axis:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pp={P_axis}")
    if cfg.n_heads % T_axis:
        raise ValueError(f"n_heads={cfg.n_heads} not divisible by tp={T_axis}")

    def block_3d(h, layer):
        B, Tm, d = h.shape
        H_local = cfg.n_heads // T_axis
        hd = cfg.head_dim

        x1 = w._rmsnorm(h, layer["ln1"])
        # column-parallel qkv: this tp rank computes H/tp whole heads
        q = jnp.einsum("btd,de->bte", x1, layer["wq"],
                       preferred_element_type=jnp.bfloat16)
        k = jnp.einsum("btd,de->bte", x1, layer["wk"],
                       preferred_element_type=jnp.bfloat16)
        v = jnp.einsum("btd,de->bte", x1, layer["wv"],
                       preferred_element_type=jnp.bfloat16)
        q = q.reshape(B, Tm, H_local, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, Tm, H_local, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, Tm, H_local, hd).transpose(0, 2, 1, 3)
        o = w._sdpa(q, k, v)  # shared causal-attention core
        o = o.transpose(0, 2, 1, 3).reshape(B, Tm, H_local * hd)
        # row-parallel o-projection: partial sums → one psum over tp
        o_part = jnp.einsum("bte,ed->btd", o, layer["wo"],
                            preferred_element_type=jnp.float32)
        h = h + lax.psum(o_part, "tp").astype(jnp.bfloat16)

        x2 = w._rmsnorm(h, layer["ln2"])
        up = jnp.einsum("btd,df->btf", x2, layer["w_up"],
                        preferred_element_type=jnp.bfloat16)
        act = jax.nn.gelu(up)
        down_part = jnp.einsum("btf,fd->btd", act, layer["w_down"],
                               preferred_element_type=jnp.float32)
        h = h + lax.psum(down_part, "tp").astype(jnp.bfloat16)
        return h

    def local_layers(x, blocks):
        def body(h, layer):
            return block_3d(h, layer), None

        x, _ = lax.scan(jax.checkpoint(body), x, blocks)
        return x

    def pipeline_body(params, tokens):
        stage = lax.axis_index("pp")
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        B, Tm = inputs.shape
        if B % M:
            raise ValueError(f"local batch {B} not divisible by microbatches {M}")
        mb = B // M

        x = params["embed"][inputs].astype(jnp.bfloat16)
        x_mb = x.reshape(M, mb, Tm, cfg.d_model)
        xs = jnp.concatenate(
            [x_mb, jnp.zeros((P_axis - 1, mb, Tm, cfg.d_model), x.dtype)]
        )

        def tick(recv, xt):
            inp = jnp.where(stage == 0, xt, recv)
            out = local_layers(inp, params["blocks"])
            send = lax.ppermute(
                out, "pp", [(j, (j + 1) % P_axis) for j in range(P_axis)]
            )
            return send, out

        _, outs = lax.scan(tick, jnp.zeros_like(xs[0]), xs)
        ys = outs[P_axis - 1 :]

        h = w._rmsnorm(ys, params["ln_f"])
        logits = jnp.einsum(
            "mbtd,dv->mbtv", h, params["unembed"],
            preferred_element_type=jnp.float32,
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        t_mb = targets.reshape(M, mb, Tm)
        ll = jnp.take_along_axis(logp, t_mb[..., None], axis=-1)[..., 0]
        local_loss = -jnp.mean(ll)
        loss = lax.psum(
            jnp.where(stage == P_axis - 1, local_loss, 0.0), "pp"
        )
        # activations are tp-replicated after each psum, so the loss is
        # already identical across tp; average over dp shards only
        return lax.pmean(loss, "dp")

    return shard_map(
        pipeline_body,
        mesh=mesh,
        in_specs=(_stage_param_specs_3d(), P("dp", None)),
        out_specs=P(),
        **_SHARD_MAP_KW,
    )


def make_pipeline3d_train_step(mesh: Mesh, cfg, num_microbatches: int = 2):
    """jit the dp×pp×tp train step; returns (step_fn, shard_inputs)."""
    loss_fn = make_pipeline3d_loss(mesh, cfg, num_microbatches)
    p_shard = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        _stage_param_specs_3d(),
        is_leaf=lambda x: isinstance(x, P),
    )
    token_shard = NamedSharding(mesh, P("dp", None))
    opt = w.make_optimizer(cfg)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(
        train_step,
        in_shardings=(p_shard, None, token_shard),
        out_shardings=(p_shard, None, None),
        donate_argnums=(0, 1),
    )

    def shard_inputs(params, opt_state, tokens):
        params = jax.device_put(params, p_shard)
        tokens = jax.device_put(tokens, token_shard)
        return params, opt_state, tokens

    return step, shard_inputs


def make_pipeline_train_step(mesh: Mesh, cfg, num_microbatches: int = 4):
    """jit the full pipelined train step: layer stack pp-sharded, batch
    dp-sharded, adamw update propagated through the same shardings.
    Returns (step_fn, shard_inputs) like the tp and ring siblings."""
    loss_fn = make_pipeline_loss(mesh, cfg, num_microbatches)
    p_shard = _stage_shardings(mesh)
    token_shard = NamedSharding(mesh, P("dp", None))
    opt = w.make_optimizer(cfg)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(
        train_step,
        in_shardings=(p_shard, None, token_shard),
        out_shardings=(p_shard, None, None),
        donate_argnums=(0, 1),
    )

    def shard_inputs(params, opt_state, tokens):
        params = jax.device_put(params, p_shard)
        tokens = jax.device_put(tokens, token_shard)
        return params, opt_state, tokens

    return step, shard_inputs
