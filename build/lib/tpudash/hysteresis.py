"""Consecutive-breach hysteresis shared by the alert engine and the
straggler detector.

Both subsystems run the same per-(rule, chip) state machine on every
frame: ok → pending (breaching, streak < for_cycles) → firing; any
non-breaching frame resets to ok, and keys not seen this frame resolve
implicitly (the chip left the table or recovered).  One implementation
here so the semantics cannot silently diverge.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Track:
    streak: int = 0
    firing_since: float | None = None
    last_value: float = 0.0


@dataclass
class TrackSet:
    """Streak bookkeeping over (rule, chip)-style keys."""

    _tracks: dict = field(default_factory=dict)

    def hit(self, key, for_cycles: int, now: float) -> "tuple[Track, bool]":
        """Record one breaching frame for ``key``; returns the track and
        whether it has reached the firing state (stamping firing_since on
        the transition)."""
        track = self._tracks.get(key)
        if track is None:
            track = self._tracks[key] = Track()
        track.streak += 1
        firing = track.streak >= for_cycles
        if firing and track.firing_since is None:
            track.firing_since = now
        return track, firing

    def resolve_unseen(self, seen: set) -> None:
        """Drop every key not breaching this frame — its streak restarts
        from zero on the next breach."""
        for key in list(self._tracks):
            if key not in seen:
                del self._tracks[key]

    def items(self):
        return self._tracks.items()

    def __len__(self) -> int:
        return len(self._tracks)
