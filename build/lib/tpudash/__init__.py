"""tpudash — TPU-native Kubernetes metrics dashboard.

A ground-up, TPU-first rebuild of the capabilities of
``ontheklaud/k8s-rocm-metrics-dashboard`` (reference: ``app.py``, 488 lines):
a live dashboard over Prometheus-scraped accelerator hardware metrics.

Where the reference polls ``amd_gpu_*`` series from a ROCm node exporter and
renders per-GPU Plotly gauges in a blocking Streamlit loop
(reference app.py:153-227, 320-486), tpudash:

- speaks a clean ``MetricsSource`` seam (Prometheus HTTP / static fixture /
  live on-chip JAX probe) so the whole stack tests without a cluster,
- models TPU pod-slice topology (v4/v5e/v5p/v6e torus coordinates) and renders
  a per-chip topology heatmap that scales to 256+ chips, where the
  reference's one-figure-per-metric-per-device pattern cannot,
- ships the node-exporter side too: on-chip probes (MXU matmul FLOPs, HBM
  bandwidth via Pallas, ICI collective bandwidth over a jax Mesh) exported in
  Prometheus text format — the reference only *consumed* such an exporter,
- serves an async (aiohttp) dashboard instead of a blocking
  ``while True: time.sleep`` Streamlit script.

Layer map (mirrors SURVEY.md §1, bottom-up):
  L1  config / registry / colors / schema / topology
  L2  sources/ + normalize.py        (data acquisition & normalization)
  L3  viz/                           (figure builders, pure plotly-JSON dicts)
  L4  app/                           (dashboard server / UI shell)
  aux ops/ parallel/ models/         (on-chip probe + demo-workload sources)
      exporter/                      (Prometheus exposition endpoint)
"""

__version__ = "0.1.0"

from tpudash.config import Config, load_config  # noqa: F401
from tpudash.registry import (  # noqa: F401
    TPU_GENERATIONS,
    TpuGeneration,
    resolve_generation,
)
from tpudash.colors import COLOR_BANDS, color_for_value  # noqa: F401
