"""TPU node exporter.

The reference *consumes* a ROCm node exporter that lives outside its repo
(SURVEY.md §2: the amd_gpu_* series are produced elsewhere and scraped by
Prometheus, reference app.py:167-176).  tpudash ships that missing half for
TPU hosts: an HTTP ``/metrics`` endpoint in Prometheus text exposition
format, fed by the on-chip probe source (tpudash.sources.probe), suitable
as a scrape target for a cluster Prometheus — the same deployment shape as
the GKE tpu-device-plugin metrics endpoint (BASELINE.json configs[1-2]).
"""

from tpudash.exporter.textfmt import encode_samples, parse_text_format  # noqa: F401
