"""``python -m tpudash.exporter`` — run the TPU node exporter."""

from tpudash.exporter.server import run

if __name__ == "__main__":
    run()
