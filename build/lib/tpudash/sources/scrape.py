"""Scrape source — consume an exporter's /metrics endpoint directly.

The minimal two-process deployment: ``python -m tpudash.exporter`` on a TPU
host, dashboard pointed straight at it (TPUDASH_SOURCE=scrape,
TPUDASH_SCRAPE_URL=http://host:9100/metrics) — the reference's
exporter→Prometheus→dashboard pipeline (app.py:153-227) minus the
Prometheus middleman, for single-host setups (BASELINE.json configs[1]).
"""

from __future__ import annotations

import requests

from tpudash.config import Config
from tpudash.sources.base import MetricsSource, SourceError, parse_text_bytes


class ScrapeSource(MetricsSource):
    name = "scrape"

    def __init__(self, cfg: Config, session: "requests.Session | None" = None):
        self.cfg = cfg
        self.session = session or requests.Session()

    def fetch(self):
        try:
            resp = self.session.get(self.cfg.scrape_url, timeout=self.cfg.http_timeout)
            resp.raise_for_status()
            text = resp.text
        except requests.RequestException as e:
            raise SourceError(f"scrape of {self.cfg.scrape_url} failed: {e}") from e
        samples = parse_text_bytes(text)
        if not samples:
            raise SourceError(
                f"{self.cfg.scrape_url} exposed no chip-labeled TPU series"
            )
        return samples

    def close(self) -> None:
        self.session.close()
