"""Joined multi-endpoint source — the multi-slice (DCN) scrape join.

BASELINE.json configs[4] (multi-slice v5p 2×256) needs series from more
than one scrape domain: each slice's metrics typically land in its own
Prometheus (or its own exporter), and the dashboard must render the union
with unambiguous slice labels.  The reference is single-endpoint by
construction (one PROMETHEUS_METRICS_ENDPOINT, app.py:22, and a discovery
trick that scopes it to a single node, app.py:157-164) — this join is the
capability it could not express (SURVEY.md §7 hard part d).

Endpoint spec syntax (``TPUDASH_MULTI_ENDPOINTS``, comma-separated):

    [slice_name=]url

- ``url`` ending in ``/metrics`` → direct exporter scrape (ScrapeSource);
  anything else → Prometheus instant-query endpoint (PrometheusSource).
- ``slice_name=`` relabels every sample's slice id from that child, so two
  Prometheus servers that both call their local slice ``slice-0`` join
  without colliding.

Partial-failure policy: one slice's scrape failing must not blank the
other slices (the reference blanks the whole page on any fetch error,
app.py:225-227).  fetch() returns the union of the healthy children and
records per-child errors in ``last_errors``; it raises only when every
child fails.
"""

from __future__ import annotations

import dataclasses
import logging

from tpudash.config import Config
from tpudash.schema import SampleBatch
from tpudash.sources.base import MetricsSource, SourceError

log = logging.getLogger("tpudash.sources.multi")


@dataclasses.dataclass(frozen=True)
class EndpointSpec:
    url: str
    slice_name: str | None  # None = keep the child's own slice labels

    @classmethod
    def parse(cls, item: str) -> "EndpointSpec":
        item = item.strip()
        if not item:
            raise ValueError("empty endpoint spec")
        slice_name = None
        if "=" in item.split("://", 1)[0]:  # '=' before the scheme → prefix
            slice_name, item = item.split("=", 1)
            slice_name = slice_name.strip()
        return cls(url=item.strip(), slice_name=slice_name)


def parse_endpoints(spec: str) -> list[EndpointSpec]:
    eps = [EndpointSpec.parse(s) for s in spec.split(",") if s.strip()]
    if not eps:
        raise ValueError(
            "multi source needs TPUDASH_MULTI_ENDPOINTS "
            "(comma-separated [slice_name=]url)"
        )
    return eps


def _child_for(ep: EndpointSpec, cfg: Config) -> MetricsSource:
    if ep.url.rstrip("/").endswith("/metrics"):
        from tpudash.sources.scrape import ScrapeSource

        return ScrapeSource(dataclasses.replace(cfg, scrape_url=ep.url))
    from tpudash.sources.prometheus import PrometheusSource

    return PrometheusSource(dataclasses.replace(cfg, prometheus_endpoint=ep.url))


class MultiSource(MetricsSource):
    name = "multi"

    def __init__(self, cfg: Config, children: list | None = None):
        """children: optional pre-built [(EndpointSpec, MetricsSource)] —
        tests inject fakes here; production builds from cfg.multi_endpoints."""
        self.cfg = cfg
        if children is None:
            children = [
                (ep, _child_for(ep, cfg))
                for ep in parse_endpoints(cfg.multi_endpoints)
            ]
        self.children: list = children
        self.last_errors: dict[str, str] = {}

    def fetch(self):
        results = []  # per healthy child: list[Sample] or SampleBatch
        errors: dict[str, str] = {}
        for ep, child in self.children:
            label = ep.slice_name or ep.url
            try:
                got = child.fetch()
            except SourceError as e:
                errors[label] = str(e)
                log.warning("multi: child %s failed: %s", label, e)
                continue
            is_batch = isinstance(got, SampleBatch)
            if ep.slice_name is not None:
                child_slices = (
                    set(got.slices) if is_batch else {s.chip.slice_id for s in got}
                )
                if len(child_slices) > 1:
                    # relabeling a multi-slice child collapses distinct
                    # (slice, chip) keys onto one name → duplicate rows
                    log.warning(
                        "multi: relabeling child %s which emits %d slices "
                        "%s — chip keys may collide",
                        label, len(child_slices), sorted(child_slices),
                    )
                if is_batch:
                    got = got.relabel_slice(ep.slice_name)
                else:
                    got = [
                        dataclasses.replace(
                            s, chip=dataclasses.replace(s.chip, slice_id=ep.slice_name)
                        )
                        for s in got
                    ]
            results.append(got)
        self.last_errors = errors
        if not any(len(r) for r in results):
            detail = "; ".join(f"{k}: {v}" for k, v in errors.items())
            raise SourceError(f"all {len(self.children)} endpoints failed: {detail}")
        if all(isinstance(r, SampleBatch) for r in results):
            return SampleBatch.concat(results)
        # mixed representations (e.g. a synthetic child among scrapes):
        # flatten to the Sample-list path
        samples: list = []
        for r in results:
            samples.extend(r.to_samples() if isinstance(r, SampleBatch) else r)
        return samples
