"""Live on-chip probe source.

Turns local JAX probes into the same Sample stream the Prometheus source
produces, so the dashboard can monitor the chip it is running on with zero
cluster infrastructure (BASELINE.json configs[1]: "single TPU VM: libtpu
metrics → local Prometheus" — here without even the Prometheus hop):

- tpu_tensorcore_utilization  ← achieved/peak bf16 TFLOP/s (MXU probe)
- tpu_hbm_used/total_bytes    ← allocator memory stats (falls back to the
                                generation's capacity for the total)
- tpu_hbm_bandwidth_gbps      ← Pallas streaming probe (extra series)
- tpu_ici_tx/rx_bytes_per_second ← ring / all-gather collective probes
                                   (multi-device hosts only)
- tpu_ici_link_xp/xn_bytes_per_second ← forward/reverse ppermute rings over
                                   the local 1D ring's two x cables

Probe cost is bounded by config (sizes/iters) and heavyweight probes run at
most once per ``probe_heavy_interval`` seconds — in between, the last
measurement is re-emitted (hardware counters vs. sampling cadence being the
classic exporter trade-off).
"""

from __future__ import annotations

import logging
import threading
import time

import jax

log = logging.getLogger(__name__)

from tpudash.config import Config
from tpudash.registry import TPU_GENERATIONS, resolve_generation
from tpudash.schema import (
    HBM_BANDWIDTH,
    HBM_TOTAL,
    HBM_USED,
    ICI_LINK_SERIES,
    ICI_RX,
    ICI_TX,
    TENSORCORE_UTIL,
    ChipKey,
    Sample,
)
from tpudash.sources.base import MetricsSource, SourceError


def _generation_for_device(dev) -> str | None:
    from tpudash.registry import resolve_generation_from_device_kind

    gen = resolve_generation_from_device_kind(getattr(dev, "device_kind", ""))
    return gen.name if gen else None


class ProbeSource(MetricsSource):
    name = "probe"

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.matmul_size = int(cfg.extra.get("probe_matmul_size", 2048))
        self.matmul_iters = int(cfg.extra.get("probe_matmul_iters", 16))
        self.hbm_mb = int(cfg.extra.get("probe_hbm_mb", 256))
        self.hbm_k1 = int(cfg.extra.get("probe_hbm_k1", 4))
        self.hbm_k2 = int(cfg.extra.get("probe_hbm_k2", 44))
        if self.hbm_k2 <= self.hbm_k1:
            raise ValueError(
                f"probe_hbm_k2 ({self.hbm_k2}) must exceed probe_hbm_k1 "
                f"({self.hbm_k1})"
            )
        self.ici_mb = int(cfg.extra.get("probe_ici_mb", 16))
        self.heavy_interval = float(cfg.extra.get("probe_heavy_interval", 30.0))
        self._last_heavy: float = 0.0
        self._cache: dict[str, float] = {}
        #: serializes heavy probe runs (startup warmup vs first scrape)
        self._heavy_lock = threading.Lock()
        self._refresh_thread: "threading.Thread | None" = None

    # -- probes --------------------------------------------------------------
    def _run_heavy_probes(self) -> dict:
        """One full probe batch as a NEW dict — callers swap it in
        atomically, so a batch that fails partway never leaves a
        half-populated cache behind (a partial cache would crash the next
        scrape with a KeyError instead of a clean SourceError)."""
        from tpudash.ops.probes import hbm_bandwidth_probe, matmul_flops_probe

        fresh: dict[str, float] = {}
        # per-device placement: each chip gets its OWN measurement (a shared
        # number would hide per-chip divergence, e.g. one chip saturated by
        # another process)
        for i, dev in enumerate(jax.local_devices()):
            mm = matmul_flops_probe(
                self.matmul_size, self.matmul_iters, device=dev
            )
            fresh[f"tflops_{i}"] = mm.value
            hbm = hbm_bandwidth_probe(
                self.hbm_mb, k1=self.hbm_k1, k2=self.hbm_k2, device=dev
            )
            fresh[f"hbm_gbps_{i}"] = hbm.value

        if jax.local_device_count() > 1:
            from tpudash.parallel.collectives import (
                all_gather_bandwidth_probe,
                ppermute_ring_bandwidth_probe,
            )
            from tpudash.parallel.mesh import build_mesh

            # local devices only: in multi-process runtimes jax.devices() is
            # global and would not match local_device_count
            mesh = build_mesh(
                {"tp": jax.local_device_count()}, devices=jax.local_devices()
            )
            tx = ppermute_ring_bandwidth_probe(mesh, "tp", self.ici_mb)
            rx = all_gather_bandwidth_probe(mesh, "tp", self.ici_mb)
            fresh["ici_tx"] = tx.value * 1e9
            fresh["ici_rx"] = rx.value * 1e9
            # direction-resolved: the local 1D ring is the x axis; the
            # forward (+1) and reverse (−1) shifts exercise each chip's
            # two x cables separately.  A link's series is combined tx+rx:
            # chip i transmits on x+ during the forward ring and receives
            # on it during the reverse ring.
            rev = ppermute_ring_bandwidth_probe(
                mesh, "tp", self.ici_mb, reverse=True
            )
            # the probe pair loads both cables symmetrically, so the two
            # directions measure equal unless one cable is degraded — in
            # which case BOTH rings slow and the drill-down still points
            # at this chip's x pair
            fresh["ici_link_xp"] = (tx.value + rev.value) * 1e9
            fresh["ici_link_xn"] = (tx.value + rev.value) * 1e9
        return fresh

    def _refresh_heavy(self) -> None:
        """Background heavy-probe refresh; failures keep the last good
        measurements (and log) rather than failing a scrape that can
        still serve them."""
        try:
            with self._heavy_lock:
                self._cache = self._run_heavy_probes()
        except Exception as e:  # noqa: BLE001 — stale beats absent
            log.warning("background probe refresh failed: %s", e)
        finally:
            # stamped on failure too: retries happen at heavy_interval
            # cadence, not one new thread + warning per scrape forever
            self._last_heavy = time.monotonic()
            self._refresh_thread = None

    def flush_refresh(self, timeout: float = 30.0) -> None:
        """Wait for an in-flight background refresh (tests, shutdown)."""
        t = self._refresh_thread
        if t is not None:
            t.join(timeout)

    def fetch(self):
        try:
            devices = jax.local_devices()
        except Exception as e:  # jax init failure
            raise SourceError(f"jax unavailable: {e}") from e
        if not devices:
            raise SourceError("no local jax devices")

        now = time.monotonic()
        if not self._cache:
            # Nothing to serve yet: the very first run pays the XLA compile
            # cost in-line (tens of seconds on a cold chip — exporter
            # startup warms this so a Prometheus scrape normally never
            # does).  Double-checked under the lock: a scrape racing the
            # warmup waits for it instead of compiling twice.
            with self._heavy_lock:
                if not self._cache:
                    try:
                        self._cache = self._run_heavy_probes()
                    except Exception as e:
                        raise SourceError(f"probe failed: {e}") from e
                    self._last_heavy = time.monotonic()
        elif (
            now - self._last_heavy >= self.heavy_interval
            and self._refresh_thread is None
        ):
            # Stale cache: refresh OFF the scrape path.  The scrape serves
            # the previous measurements immediately — a 10s Prometheus
            # scrape timeout must never lose a cycle to a 100ms+ probe
            # batch, let alone a recompile after a topology change.
            t = threading.Thread(target=self._refresh_heavy, daemon=True)
            self._refresh_thread = t
            t.start()

        from tpudash.ops.probes import hbm_memory_stats

        dev = devices[0]
        gen_name = _generation_for_device(dev) or self.cfg.generation
        gen = resolve_generation(gen_name) or TPU_GENERATIONS["v5e"]
        accel = gen.accelerator_types[0]
        host = "localhost"
        samples: list[Sample] = []

        def emit(metric: str, chip_id: int, value: float) -> None:
            samples.append(
                Sample(
                    metric=metric,
                    value=value,
                    chip=ChipKey(slice_id="local", host=host, chip_id=chip_id),
                    accelerator_type=accel,
                )
            )

        for i, d in enumerate(devices):
            mem = hbm_memory_stats(d)
            hbm_total = mem["total_bytes"] or gen.hbm_gib * 1024**3
            util_pct = min(
                100.0,
                self._cache[f"tflops_{i}"] / gen.peak_bf16_tflops * 100.0,
            )
            emit(TENSORCORE_UTIL, i, util_pct)
            emit(HBM_USED, i, mem["used_bytes"])
            emit(HBM_TOTAL, i, hbm_total)
            emit(HBM_BANDWIDTH, i, self._cache[f"hbm_gbps_{i}"])
            if "ici_tx" in self._cache:
                # ring/all-gather are symmetric: every chip moves the same
                # bytes, so the per-chip value is genuinely per-chip
                emit(ICI_TX, i, self._cache["ici_tx"])
                emit(ICI_RX, i, self._cache["ici_rx"])
            if "ici_link_xp" in self._cache:
                emit(ICI_LINK_SERIES["xp"], i, self._cache["ici_link_xp"])
                emit(ICI_LINK_SERIES["xn"], i, self._cache["ici_link_xn"])
        return samples
