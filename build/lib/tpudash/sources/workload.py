"""Workload source — live metrics from a real training loop on this host.

Where the probe source measures chip *capability* with dedicated kernels,
the workload source trains the demo transformer continuously in the
background and reports what the chip is actually doing: TensorCore
utilization derived from achieved step FLOP/s, HBM occupancy from the
allocator, plus workload-specific series (loss, steps/s) that land in the
stats table.  TPUDASH_SOURCE=workload gives a self-contained moving demo
on any TPU VM (or the CPU test mesh).

Workload sizing comes from Config.extra (workload_d_model etc.) — defaults
are small enough to stay responsive at the dashboard's refresh cadence.
"""

from __future__ import annotations

import jax

from tpudash.config import Config
from tpudash.registry import (
    TPU_GENERATIONS,
    resolve_generation,
    resolve_generation_from_device_kind,
)
from tpudash.schema import (
    HBM_TOTAL,
    HBM_USED,
    TENSORCORE_UTIL,
    ChipKey,
    Sample,
)
from tpudash.sources.base import MetricsSource, SourceError

#: workload-only series (appear in stats/CLI, not as gauges)
WORKLOAD_LOSS = "tpu_workload_loss"
WORKLOAD_STEPS_PER_S = "tpu_workload_steps_per_second"
WORKLOAD_TFLOPS = "tpu_workload_achieved_tflops"


class WorkloadSource(MetricsSource):
    name = "workload"

    def __init__(self, cfg: Config):
        from tpudash.models.runner import WorkloadRunner
        from tpudash.models.workload import WorkloadConfig

        self.cfg = cfg
        # defaults sized to keep a v5e-class chip visibly busy (~10 TFLOP per
        # fwd+bwd step) while compiling in well under a minute
        wcfg = WorkloadConfig(
            vocab=int(cfg.extra.get("workload_vocab", 2048)),
            d_model=int(cfg.extra.get("workload_d_model", 1024)),
            n_heads=int(cfg.extra.get("workload_n_heads", 16)),
            n_layers=int(cfg.extra.get("workload_n_layers", 8)),
            d_ff=int(cfg.extra.get("workload_d_ff", 4096)),
            seq=int(cfg.extra.get("workload_seq", 512)),
            batch=int(cfg.extra.get("workload_batch", 16)),
        )
        self.runner = WorkloadRunner(
            wcfg,
            steps_per_sync=int(cfg.extra.get("workload_steps_per_sync", 8)),
            checkpoint_dir=cfg.workload_checkpoint_dir,
            checkpoint_every=cfg.workload_checkpoint_every,
        )

    def fetch(self):
        from tpudash.ops.probes import hbm_memory_stats

        if not self.runner.running:
            self.runner.start()
        try:
            m = self.runner.metrics()
        except RuntimeError as e:
            raise SourceError(str(e)) from e

        devices = jax.local_devices()
        kind = getattr(devices[0], "device_kind", "") or ""
        gen = (
            resolve_generation_from_device_kind(kind)
            or resolve_generation(self.cfg.generation)
            or TPU_GENERATIONS["v5e"]
        )
        accel = gen.accelerator_types[0]

        # the sharded step spreads FLOPs across all local devices
        per_chip_tflops = m["achieved_tflops"] / max(1, len(devices))
        util = min(100.0, per_chip_tflops / gen.peak_bf16_tflops * 100.0)

        samples: list[Sample] = []
        for i, d in enumerate(devices):
            chip = ChipKey(slice_id="local", host="localhost", chip_id=i)
            mem = hbm_memory_stats(d)
            total = mem["total_bytes"] or gen.hbm_gib * 1024**3
            for metric, value in (
                (TENSORCORE_UTIL, util),
                (HBM_USED, mem["used_bytes"]),
                (HBM_TOTAL, total),
                (WORKLOAD_LOSS, m["loss"]),
                (WORKLOAD_STEPS_PER_S, m["steps_per_second"]),
                (WORKLOAD_TFLOPS, per_chip_tflops),
            ):
                if value == value:  # skip NaN (no step completed yet)
                    samples.append(
                        Sample(
                            metric=metric,
                            value=float(value),
                            chip=chip,
                            accelerator_type=accel,
                        )
                    )
        if not samples:
            raise SourceError("workload has not produced metrics yet")
        return samples

    def close(self) -> None:
        self.runner.stop()
