#!/usr/bin/env python
"""Vendor plotly.min.js into tpudash/app/assets/ from the pinned wheel.

The reference gets offline charting for free: plotly is a pinned Python
dependency (reference uv.lock pins plotly 6.0.1) and Streamlit serves
every browser asset itself.  tpudash vendors only what the browser needs
— the single minified bundle the plotly wheel carries at
``plotly/package_data/plotly.min.js`` — and serves it from the dashboard
at ``/static/plotly.min.js``, so an air-gapped deployment renders the
full interactive UI with zero egress.

Three ways in, tried in order when no flag forces one:

1. ``--wheel PATH`` — extract from a plotly wheel file (fully offline).
2. An already-importable ``plotly`` package (its installed package_data).
3. ``pip download`` of the pinned version (needs network — this is a
   BUILD-time step; the Dockerfile runs it in the build stage, never at
   runtime).

Paths 1 and 3 verify the wheel's sha256 against ``PLOTLY_WHEEL_SHA256``
before extracting — the served bundle runs in every dashboard browser,
so a version-only pin would trust whatever the index hands the build.
Path 2 trusts the environment's own install integrity (the wheel is
gone by then); ``--sha256 HEX`` overrides the pin for a deliberately
different wheel.

Usage:
    python deploy/fetch_plotly.py                      # auto (2 then 3)
    python deploy/fetch_plotly.py --wheel plotly-*.whl # offline
    python deploy/fetch_plotly.py --dest some/dir      # custom drop point
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile
import zipfile

#: The plotly PYTHON wheel whose bundled plotly.js exactly matches the
#: page contract (html.PLOTLY_VERSION = 2.32.0): plotly.py 5.22.0 ships
#: plotly.js 2.32.0 in package_data.  The reference pins plotly 6.0.1
#: (which bundles plotly.js 3.x); tpudash pins by the JS version instead
#: so the vendored bundle and the page's CDN fallback are the SAME
#: plotly.js — figure dicts render identically on either load path.
PLOTLY_PIN = "5.22.0"
PLOTLY_JS_VERSION = "2.32.0"
#: sha256 of ``plotly-5.22.0-py3-none-any.whl`` as published on PyPI —
#: the pip-download path used to trust the index/mirror at image-build
#: time (ADVICE r5): a compromised index could ship attacker JS to every
#: dashboard browser.  Now the wheel bytes must hash to this before the
#: bundle is extracted.  Recompute when bumping PLOTLY_PIN:
#:   pip download --no-deps plotly==<pin> -d /tmp/w && sha256sum /tmp/w/*.whl
#: (or read it off pypi.org/project/plotly/<pin>/#files).
PLOTLY_WHEEL_SHA256 = (
    "68fc1901f098daeb233cc3dd44ec9dc31fb3ca4f4e53189344199c43496ed006"
)
ASSET_IN_WHEEL = "plotly/package_data/plotly.min.js"
DEFAULT_DEST = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tpudash",
    "app",
    "assets",
)


def _write_atomic(data: bytes, dest: str) -> str:
    out = os.path.join(dest, "plotly.min.js")
    tmp = out + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, out)  # atomic: a killed build can't leave half a bundle
    return out


def from_wheel(
    wheel_path: str, dest: str, sha256: "str | None" = PLOTLY_WHEEL_SHA256
) -> str:
    # the served URL is stamped with the plotly.js version the PIN's
    # wheel bundles — extracting any other wheel (e.g. the reference's
    # 6.0.1, which carries plotly.js 3.x) would serve the wrong major
    # version under that URL.  Wheel filenames are PEP 427
    # (name-version-...), so the check is cheap and offline.
    base = os.path.basename(wheel_path)
    parts = base.split("-")
    if len(parts) >= 2 and parts[0] == "plotly" and parts[1] != PLOTLY_PIN:
        raise SystemExit(
            f"{base} is plotly {parts[1]}, but the page contract needs "
            f"{PLOTLY_PIN} (bundles plotly.js {PLOTLY_JS_VERSION})"
        )
    if sha256:
        import hashlib

        h = hashlib.sha256()
        with open(wheel_path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        got = h.hexdigest()
        if got != sha256:
            raise SystemExit(
                f"{base} sha256 mismatch:\n  expected {sha256}\n  got      "
                f"{got}\nRefusing to vendor a bundle the pin does not vouch "
                "for (compromised index/mirror, or a stale PLOTLY_WHEEL_SHA256"
                " after a pin bump — see deploy/fetch_plotly.py)."
            )
    with zipfile.ZipFile(wheel_path) as zf:
        try:
            data = zf.read(ASSET_IN_WHEEL)
        except KeyError:
            raise SystemExit(
                f"{wheel_path} has no {ASSET_IN_WHEEL} — not a plotly wheel?"
            )
    return _write_atomic(data, dest)


def from_installed(dest: str) -> "str | None":
    try:
        import plotly
    except ImportError:
        return None
    if getattr(plotly, "__version__", None) != PLOTLY_PIN:
        # whatever happens to be installed is NOT the pinned bundle —
        # fall through to pip download rather than silently vendoring a
        # different plotly.js than the page contract names
        print(
            f"installed plotly {getattr(plotly, '__version__', '?')} "
            f"!= pin {PLOTLY_PIN}; ignoring it",
            file=sys.stderr,
        )
        return None
    src = os.path.join(
        os.path.dirname(plotly.__file__), "package_data", "plotly.min.js"
    )
    if not os.path.isfile(src):
        return None
    with open(src, "rb") as f:
        return _write_atomic(f.read(), dest)


def from_pip_download(dest: str, sha256: "str | None" = PLOTLY_WHEEL_SHA256) -> str:
    with tempfile.TemporaryDirectory() as tmp:
        subprocess.run(
            [
                sys.executable,
                "-m",
                "pip",
                "download",
                "--no-deps",
                f"plotly=={PLOTLY_PIN}",
                "-d",
                tmp,
            ],
            check=True,
        )
        wheels = [f for f in os.listdir(tmp) if f.endswith(".whl")]
        if not wheels:
            raise SystemExit("pip download produced no wheel")
        return from_wheel(os.path.join(tmp, wheels[0]), dest, sha256=sha256)


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--wheel", help="extract from this plotly wheel file")
    ap.add_argument("--dest", default=DEFAULT_DEST, help="drop directory")
    ap.add_argument(
        "--sha256",
        default=PLOTLY_WHEEL_SHA256,
        help="expected wheel sha256 (defaults to the pinned hash)",
    )
    args = ap.parse_args(argv)
    os.makedirs(args.dest, exist_ok=True)
    if args.wheel:
        out = from_wheel(args.wheel, args.dest, sha256=args.sha256)
    else:
        out = from_installed(args.dest) or from_pip_download(
            args.dest, sha256=args.sha256
        )
    size_kb = os.path.getsize(out) // 1024
    print(f"vendored {out} ({size_kb} KB)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
