# tpudash container image — the image deploy/dashboard.yaml and
# deploy/exporter-daemonset.yaml deploy (`tpudash:latest`).
#
# Reproducible by construction: every Python package installs from the
# committed requirements.lock (exact pins, see deploy/make_lock.py) with
# resolution disabled (--no-deps everywhere), so two builds of the same
# tree produce the same dependency set — the property the reference gets
# from its uv.lock.  The native C++ frame kernel is compiled INTO the
# image at build time; at runtime there is no compiler, no PyPI access,
# and no root.
#
#   docker build -t tpudash:latest .
#   docker run --rm -p 8050:8050 -e TPUDASH_SOURCE=synthetic tpudash:latest
#
# Notes:
# - The lock pins CPU jaxlib: fixture/synthetic/prometheus/scrape sources
#   and the exporter all work as-is.  For the on-chip probe source on a
#   real TPU node pool, layer libtpu on top (the TPU node image provides
#   it; see deploy/README.md).
# - Healthcheck uses the stdlib, not curl — the runtime stage installs no
#   extra OS packages at all.

FROM python:3.12-slim AS build
RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*
WORKDIR /src
# dependency layer first: lockfile changes invalidate from here, source
# changes don't re-download 45 packages
COPY requirements.lock ./
RUN python -m venv /opt/venv \
    && /opt/venv/bin/pip install --no-cache-dir --no-deps -r requirements.lock
COPY pyproject.toml README.md ./
COPY tpudash ./tpudash
COPY deploy/fetch_plotly.py ./deploy/fetch_plotly.py
# vendor the plotly bundle (pinned like the reference's uv.lock) into the
# package BEFORE install, so the runtime image serves the rich UI itself
# with zero egress — no CDN dependency in an air-gapped cluster
RUN /opt/venv/bin/python deploy/fetch_plotly.py --dest tpudash/app/assets
RUN /opt/venv/bin/pip install --no-cache-dir --no-deps . \
    # compile the native frame kernel into the installed package now so
    # the runtime stage needs no g++ (loader would otherwise build on
    # first use, tpudash/native/__init__.py).  -P keeps /src off
    # sys.path: with cwd importable, `import tpudash` would resolve the
    # SOURCE tree — the kernel would compile into /src (lost at the
    # stage boundary) and the asset assert would vacuously pass
    && /opt/venv/bin/python -P - <<'EOF'
import tpudash
assert "site-packages" in tpudash.__file__, (
    "checks must run against the venv install, got %r" % tpudash.__file__
)
from tpudash import native
lib = native.load()
assert lib is not None, "native frame kernel failed to compile"
print("native kernel built:", native.is_available())
from tpudash.app.assets import find_plotly_asset
asset = find_plotly_asset()
assert asset and "site-packages" in asset, (
    "vendored plotly bundle missing from the installed package: %r" % asset
)
print("plotly vendored at:", asset)
EOF

FROM python:3.12-slim
COPY --from=build /opt/venv /opt/venv
ENV PATH="/opt/venv/bin:$PATH" \
    PYTHONUNBUFFERED=1
# non-root, no shell profile, no home-directory writes needed
RUN useradd --uid 10001 --create-home --shell /usr/sbin/nologin tpudash
USER 10001
WORKDIR /home/tpudash
EXPOSE 8050
HEALTHCHECK --interval=30s --timeout=5s --start-period=20s --retries=3 \
    CMD ["python", "-c", "import os, urllib.request; urllib.request.urlopen('http://127.0.0.1:%s/healthz' % os.environ.get('TPUDASH_PORT', '8050'), timeout=4)"]
ENTRYPOINT ["tpudash"]
