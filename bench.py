"""Benchmark — prints ONE JSON line.

North-star metric (BASELINE.json): scrape→render p50 latency for a full
dashboard frame over a 256-chip v5e pod slice, all chips selected.  The
reference's implicit budget is its 5 s refresh cadence (reference app.py:24,
486): a frame must complete well inside it.  ``vs_baseline`` is therefore
(5 s budget) / (measured p50) — how many frames we could render per refresh
window (>1 beats the baseline; the reference's per-device-figure design
could not hold the budget at 256 chips, SURVEY.md §3.2).

When real accelerator hardware is present, on-chip probe numbers
(achieved matmul TFLOP/s, HBM streaming GB/s) are attached as extra keys.
"""

from __future__ import annotations

import functools
import json
import time


BUDGET_S = 5.0  # the reference's refresh cadence == our frame budget

#: compact separators, exactly as the server serializes the wire
#: (tpudash/app/server.py _dumps) — wire-size numbers must measure what
#: a subscriber actually receives
_dumps = functools.partial(json.dumps, separators=(",", ":"))
N_CHIPS = 256
N_FRAMES = 30


def _bench_service(
    per_slice: int,
    generation: str = "v5e",
    num_slices: int = 1,
    emit_links: bool = False,
    **cfg_kw,
):
    """Shared bench harness: a DashboardService over pre-serialized
    replay payloads (each timed frame pays the real production cost —
    decode the instant-query JSON off the wire via the native frame
    kernel, normalize, render — and nothing else; payload fabrication is
    setup, exactly as Prometheus's own response assembly is not the
    dashboard's cost in deployment), warmed, select-all, timer cleared."""
    from tpudash.app.service import DashboardService
    from tpudash.config import Config
    from tpudash.sources.fixture import JsonReplaySource

    cfg = Config(
        source="synthetic",
        synthetic_chips=per_slice,
        synthetic_slices=num_slices,
        generation=generation,
        **cfg_kw,
    )
    svc = DashboardService(
        cfg,
        JsonReplaySource.synthetic(
            per_slice,
            generation=generation,
            frames=8,
            num_slices=num_slices,
            emit_links=emit_links,
        ),
    )
    svc.render_frame()  # warm (imports, first pivot)
    svc.state.select_all(svc.available)
    svc.timer.history.clear()  # warm-up frames must not contaminate p50/p95
    return svc


def bench_dashboard() -> dict:
    svc = _bench_service(N_CHIPS)
    frame = None
    for _ in range(N_FRAMES):
        prev = frame
        frame = svc.render_frame()
        assert frame["error"] is None
        assert len(frame["selected"]) == N_CHIPS
        assert frame["heatmaps"], "256-chip frame must use heatmap mode"
    p50 = svc.timer.percentile(0.5)
    p95 = svc.timer.percentile(0.95)
    # wire cost per subscriber per refresh interval: the first tick's full
    # frame vs the steady-state value-only delta (tpudash/app/delta.py),
    # plus the gzip size a polling client actually downloads (the server
    # negotiates compression on /api/frame)
    import gzip

    from tpudash.app.delta import frame_delta

    payload = f"data: {_dumps(dict(frame, kind='full'))}\n\n".encode()
    delta = frame_delta(prev, frame)
    assert delta is not None, "steady-state frames must be delta-patchable"
    delta_payload = f"data: {_dumps(delta)}\n\n".encode()
    # the SSE transport gzips with per-event sync flushes over ONE shared
    # window (server.stream): measure a steady-state tick's wire bytes
    # with the full frame already in the window, as a subscriber sees it
    import zlib

    comp = zlib.compressobj(6, zlib.DEFLATED, 16 + zlib.MAX_WBITS)
    comp.compress(payload)
    comp.flush(zlib.Z_SYNC_FLUSH)
    tick_wire = len(comp.compress(delta_payload) + comp.flush(zlib.Z_SYNC_FLUSH))
    return {
        "p50_s": p50,
        "p95_s": p95,
        "sse_bytes": len(payload),
        "sse_delta_bytes": len(delta_payload),
        "sse_delta_gzip_bytes": tick_wire,
        "frame_gzip_bytes": len(gzip.compress(_dumps(frame).encode())),
    }


def bench_3d_torus() -> dict:
    """3D-torus proof (v4, 4×4×8 = 128 chips): render cost plus a geometry
    assertion that the Z-planes actually unroll side by side (8 planes of
    4×4 with 1-column gaps → 4 rows × 39 columns)."""
    svc = _bench_service(128, generation="v4")  # 4×4×8 (topology._V4_SHAPES)
    for _ in range(N_FRAMES):
        frame = svc.render_frame()
        assert frame["error"] is None
        assert frame["heatmaps"], "128-chip selection must render heatmaps"
    z = frame["heatmaps"][0]["figure"]["data"][0]["z"]
    ny, width = len(z), len(z[0])
    assert (ny, width) == (4, 8 * 4 + 7), f"bad 3D unroll: {ny}x{width}"
    return {
        "p50_s": svc.timer.percentile(0.5),
        "shape": "4x4x8",
        "grid": f"{ny}x{width}",
    }


def bench_link_detail() -> dict:
    """256 chips with direction-resolved per-link ICI series enabled
    (4 extra series per chip on the 2D torus): the per-link capability's
    full cost — bigger payload parse, 6 extra derived columns, the
    coldest-link heatmap panel, straggler link rules — must stay deep
    inside the budget too."""
    svc = _bench_service(N_CHIPS, emit_links=True)
    for _ in range(N_FRAMES):
        frame = svc.render_frame()
        assert frame["error"] is None
    panels = [h["panel"] for h in frame["heatmaps"]]
    assert "ici_link_min_gbps" in panels, "min-link heatmap must render"
    return {"p50_s": svc.timer.percentile(0.5)}


def bench_multislice() -> dict:
    """Secondary number: 2 slices × 256 chips (the BASELINE.json configs[4]
    multi-slice shape) with cross-slice DCN series, all 512 chips selected."""
    # per-slice chips: 2 × 256 = 512 chips total, DCN series on
    svc = _bench_service(N_CHIPS, generation="v5p", num_slices=2)
    for _ in range(N_FRAMES):
        frame = svc.render_frame()
        assert frame["error"] is None
        assert len(frame["selected"]) == 2 * N_CHIPS
        assert {h["slice"] for h in frame["heatmaps"]} == {"slice-0", "slice-1"}
    return {"p50_s": svc.timer.percentile(0.5)}


def cpu_reference_ms() -> float:
    """Fixed CPU workload (numpy matmul, median of 5) as a machine-speed
    reference.  The frame pipeline is pure CPU work, and this host's
    effective clock drifts ±30% with neighbors — recording the reference
    lets the regression guard compare p50s in machine-relative terms
    instead of flagging an environmental level shift as a regression."""
    import statistics
    import time as _t

    import numpy as np

    rng = np.random.default_rng(0)
    a = rng.random((1024, 1024))
    a @ a  # warm
    times = []
    for _ in range(5):
        t0 = _t.perf_counter()
        a @ a
        times.append((_t.perf_counter() - t0) * 1e3)
    return round(statistics.median(times), 2)


def cpu_reference_json_ms() -> float:
    """Second machine-speed reference, shaped like the FRAME PATH rather
    than like BLAS.  Round 4's lesson: the driver-captured p50 ran 33%
    slow while the matmul reference stayed flat (r04 7.73 ms @ ref 38.08
    vs the same code measuring 5.75 ms @ ref 38.02 on a quiet host) —
    cache-resident vectorized matmul is insensitive to the memory-latency
    and scheduler contention that actually slows the dict/string/JSON
    work a frame is made of.  This reference does fixed JSON
    encode/decode + small-object churn, so it degrades when the frame
    path would.  The regression guard prefers it when both rounds carry
    it (find_regressions)."""
    import statistics
    import time as _t

    payload = {
        f"chip-{i}": {
            "util": i * 0.37,
            "hbm": [i, i + 1, i + 2],
            "key": f"slice-{i % 4}/{i}",
        }
        for i in range(2000)
    }
    blob = json.dumps(payload)
    json.loads(blob)  # warm
    times = []
    for _ in range(5):
        t0 = _t.perf_counter()
        decoded = json.loads(blob)
        rows = sorted(
            (v["util"], k, tuple(v["hbm"])) for k, v in decoded.items()
        )
        json.dumps({k: u for u, k, _ in rows[:500]})
        times.append((_t.perf_counter() - t0) * 1e3)
    return round(statistics.median(times), 2)


def _rss_mb() -> float:
    """Resident set of this process in MB (Linux /proc, no psutil).
    Collects first so allocator slack doesn't read as growth."""
    import gc

    gc.collect()
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return round(int(line.split()[1]) / 1024.0, 1)
    return 0.0


#: BENCH_r05's measured 4096-chip JSON SSE delta — the fixed baseline the
#: binary wire format is graded against (ISSUE 10 acceptance: ≥3x smaller)
R05_JSON_DELTA_BYTES = 344627
#: ISSUE 10 hard ceiling for the 4096-chip scrape→render p50
SCALE_4096_P50_BUDGET_MS = 20.0
#: ISSUE 11 hard ceiling for the COLUMNAR full frame at 4096 chips (the
#: JSON frame is ~1.7 MB; the figure-template + cfull envelope must stay
#: under this or the columnar encoding degraded)
SCALE_4096_FULL_FRAME_BUDGET_BYTES = 300_000


def bench_scale(
    total_chips: int,
    frames: int = N_FRAMES,
    ring: int = 30,
    p50_budget_ms: "float | None" = None,
    binary_floor_bytes: "int | None" = None,
    full_frame_budget_bytes: "int | None" = None,
) -> dict:
    """Headroom PAST the 256-chip north star: p50, steady-state SSE delta
    bytes, and the memory ceiling at ``total_chips`` (4×256-chip slices,
    then 16×256) — the scaling wall the reference hits at 256 chips
    (SURVEY §3.2: per-device figures) must stay distant here.

    The boundedness proof is measured, not asserted: the trend rings are
    shortened to ``ring`` points (cfg.history_points), rendered to
    COMPLETELY full, and only then is RSS sampled — every further frame
    evicts as much as it appends, so ``rss_growth_mb`` over the timed run
    must be ~0.  Growth here means a ring, session map, or cache is not
    actually bounded at this scale."""
    from tpudash.app.delta import frame_delta

    slices = max(1, total_chips // N_CHIPS)
    svc = _bench_service(
        total_chips // slices,
        num_slices=slices,
        history_points=ring,
        # history appends are wall-clock-throttled to the refresh cadence;
        # 0 makes every bench frame append so the ring provably cycles
        refresh_interval=0.0,
    )
    frame = None
    for _ in range(ring + 2):  # fill both rings to their ceiling
        frame = svc.render_frame()
    assert len(svc.chip_history) == ring, "ring must be full before sampling"
    svc.timer.history.clear()
    rss_full = _rss_mb()
    for _ in range(frames):
        prev = frame
        frame = svc.render_frame()
        assert frame["error"] is None
        assert len(frame["selected"]) == total_chips
    delta = frame_delta(prev, frame)
    assert delta is not None
    # the binary twin of the steady-state delta (tpudash/app/wire.py):
    # measured as the complete framed stream event — exactly the bytes a
    # ?format=bin subscriber receives per tick — plus the seal-side cost
    # of producing it (frame_delta + encode, the marginal work the
    # binary tier adds to one cohort seal)
    import statistics

    from tpudash.app import wire

    bin_ms = []
    for _ in range(5):
        t0 = time.perf_counter()
        d = frame_delta(prev, frame)
        buf = wire.encode_delta(prev, d)
        bin_ms.append((time.perf_counter() - t0) * 1e3)
    bin_event = wire.bin_event(wire.EVT_DELTA, "1-1", buf)
    assert wire.decode_delta(buf, prev) == delta, (
        "binary delta must round-trip to the JSON delta exactly"
    )
    p50 = svc.timer.percentile(0.5)
    if p50_budget_ms is not None:
        # ISSUE 10 acceptance: the columnar hot path must hold the frame
        # budget at this scale — a hard gate, not a trend check
        assert p50 * 1e3 <= p50_budget_ms, (
            f"scale_{total_chips} p50 {p50 * 1e3:.1f}ms blew the "
            f"{p50_budget_ms:g}ms budget"
        )
    if binary_floor_bytes is not None:
        assert len(bin_event) <= binary_floor_bytes, (
            f"binary delta {len(bin_event)}B at {total_chips} chips — "
            f"not ≥3x smaller than the {R05_JSON_DELTA_BYTES}B r05 JSON delta"
        )
    # the COLUMNAR full frame (ISSUE 11): figure-structure template +
    # per-tick numeric sections as the self-contained envelope binary
    # /api/frame serves.  Template and cfull are also measured apart —
    # a streaming client pays the template once per epoch and the cfull
    # per full event.
    frame_j = json.loads(_dumps(frame))
    full_ms = []
    for _ in range(3):
        t0 = time.perf_counter()
        tpl_buf = wire.encode_template(frame_j, "bench")
        cfull_buf = wire.encode_cfull(frame_j, "bench")
        envelope = wire.fullc_envelope(tpl_buf, cfull_buf)
        full_ms.append((time.perf_counter() - t0) * 1e3)
    assert wire.decode_frame(envelope) == frame_j, (
        "columnar full frame must round-trip exactly"
    )
    json_frame_bytes = len(_dumps(frame_j).encode())
    if full_frame_budget_bytes is not None:
        # ISSUE 11 acceptance: full-frame bytes must stop scaling with
        # JSON size — a hard gate, not a trend check
        assert len(envelope) <= full_frame_budget_bytes, (
            f"columnar full frame {len(envelope)}B at {total_chips} "
            f"chips blew the {full_frame_budget_bytes}B budget "
            f"(JSON frame is {json_frame_bytes}B)"
        )
    return {
        "p50_s": p50,
        "sse_delta_bytes": len(f"data: {_dumps(delta)}\n\n".encode()),
        "binary_delta_bytes": len(bin_event),
        "bin_seal_ms": round(statistics.median(bin_ms), 2),
        "full_frame_bytes": len(envelope),
        "full_frame_tpl_bytes": len(tpl_buf),
        "full_frame_cfull_bytes": len(cfull_buf),
        "full_frame_json_bytes": json_frame_bytes,
        "full_frame_encode_ms": round(statistics.median(full_ms), 2),
        "rss_mb": _rss_mb(),
        "rss_growth_mb": round(_rss_mb() - rss_full, 1),
    }


def bench_bus_fanout(worker_counts=(1, 2, 4), seals=48) -> dict:
    """ISSUE 11 tentpole (c): bus publish cost vs worker count.

    One in-process BusPublisher (shm seal ring) fans realistic-sized
    seals out to N mirror processes (REAL subprocesses, so their drain
    CPU cannot pollute the publisher's measurement).  Reported per N:
    publisher-process CPU per published seal (publish + descriptor
    sends + drain to the socket, measured with time.process_time from
    first publish to full drain) and wire bytes per worker per seal.

    Hard guard (shm mode): CPU per seal at 4 workers must stay within
    2.5x of 1 worker — the descriptor path makes fan-out O(1) in blob
    bytes, so publish cost must NOT scale with worker count the way
    copying N×~800KB would.  In copy mode (ring unavailable) the guard
    is skipped and the mode is reported so find_regressions sees it."""
    import asyncio
    import json as _json
    import subprocess
    import sys
    import tempfile

    from tpudash.broadcast.bus import BusPublisher
    from tpudash.broadcast.cohort import CohortHub, Seal
    from tpudash.app.state import SelectionState

    # seal shaped like a 4096-chip tick: ~1.7MB JSON full + gz + binary
    blob = {
        "sse_full_raw": b"F" * 900_000,
        "sse_full_gz": b"g" * 60_000,
        "sse_delta_raw": b"D" * 340_000,
        "sse_delta_gz": b"e" * 40_000,
        "frame_raw": b"R" * 900_000,
        "frame_gz": b"h" * 60_000,
        "bin_full_raw": b"B" * 190_000,
        "bin_full_gz": b"i" * 50_000,
        "bin_delta_raw": b"b" * 83_000,
        "bin_delta_gz": b"j" * 30_000,
    }
    per_seal_blob_bytes = sum(len(v) for v in blob.values())

    reader_src = (
        "import asyncio, sys\n"
        "from tpudash.broadcast.bus import BusMirror\n"
        "async def main():\n"
        "    m = BusMirror(sys.argv[1], pid=0, index=0)\n"
        "    stop = asyncio.Event()\n"
        "    asyncio.ensure_future(m.run(stop))\n"
        "    await asyncio.Event().wait()\n"
        "asyncio.run(main())\n"
    )

    out: dict = {}
    mode = None
    cpu_per_seal: dict = {}
    for workers in worker_counts:
        tmp = tempfile.mkdtemp(prefix="tpudash-busbench-")
        path = f"{tmp}/bus.sock"

        async def run_one(path=path, workers=workers):
            hub = CohortHub(lambda s: {}, _json.dumps, window=4)
            state = SelectionState()
            state.selected = ["bench"]
            cohort = hub.resolve(state)
            # ring sized ABOVE the whole burst (48 × ~2.65MB): a lapped
            # reader mid-burst would reconnect and pollute the measured
            # CPU with snapshot traffic — capacity + pacing (below)
            # keep laps out of the measurement entirely
            pub = BusPublisher(path, hub, backlog=512, ring_mb=192)
            await pub.start()
            procs = [
                subprocess.Popen(
                    [sys.executable, "-c", reader_src, path],
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                )
                for _ in range(workers)
            ]
            try:
                for _ in range(200):
                    if len(pub.workers()) >= workers:
                        break
                    await asyncio.sleep(0.05)
                assert len(pub.workers()) >= workers, "mirrors connected"
                published = 0
                t0 = time.perf_counter()
                c0 = time.process_time()
                for seq in range(1, seals + 1):
                    seal = Seal(
                        cohort.cid,
                        seq,
                        (seq, False),
                        *[blob[n] for n in (
                            "sse_full_raw", "sse_full_gz",
                            "sse_delta_raw", "sse_delta_gz",
                            "frame_raw", "frame_gz",
                        )],
                        *[blob[n] for n in (
                            "bin_full_raw", "bin_full_gz",
                            "bin_delta_raw", "bin_delta_gz",
                        )],
                    )
                    pub.publish_seal(seal)
                    published += 1
                    await asyncio.sleep(0)  # let drains run
                    # pace on drain: bound how far any reader can lag
                    # so descriptors are consumed long before the head
                    # could ever wrap to them (sleeps cost wall time,
                    # not the process_time being measured)
                    for _ in range(200):
                        ws = pub.workers()
                        if all(w["queued"] <= 2 for w in ws):
                            break
                        await asyncio.sleep(0.005)
                # drain fully: every connection's queue empty + sent
                for _ in range(400):
                    ws = pub.workers()
                    if ws and all(
                        w["queued"] == 0 and w["sent"] >= published
                        for w in ws
                    ):
                        break
                    await asyncio.sleep(0.025)
                cpu_ms = (time.process_time() - c0) * 1e3
                wall_ms = (time.perf_counter() - t0) * 1e3
                st = pub.stats()
                return {
                    "cpu_ms_per_seal": cpu_ms / published,
                    "wall_ms_per_seal": wall_ms / published,
                    "mode": st["ring"]["mode"],
                    "wire_bytes": (
                        st["counters"]["desc_bytes_published"]
                        + st["counters"]["blob_bytes_published"]
                    ),
                    "published": published,
                }
            finally:
                for p in procs:
                    p.kill()
                for p in procs:
                    p.wait()
                await pub.close()

        r = asyncio.run(run_one())
        mode = r["mode"]
        cpu_per_seal[workers] = r["cpu_ms_per_seal"]
        out[f"bus_fanout_cpu_ms_per_seal_{workers}w"] = round(
            r["cpu_ms_per_seal"], 3
        )
        out[f"bus_fanout_wire_bytes_per_worker_per_seal_{workers}w"] = int(
            r["wire_bytes"] / (workers * r["published"])
        )
    out["bus_fanout_mode"] = mode
    out["bus_fanout_blob_bytes_per_seal"] = per_seal_blob_bytes
    lo, hi = min(worker_counts), max(worker_counts)
    ratio = cpu_per_seal[hi] / max(cpu_per_seal[lo], 1e-9)
    out["bus_fanout_flat_ratio"] = round(ratio, 2)
    if mode == "shm":
        # the flat-in-worker-count guard: 4x the workers must not cost
        # 4x the publish CPU (descriptors, not blobs, scale with N)
        assert ratio <= 2.5, (
            f"bus publish CPU scaled with worker count ({lo}w "
            f"{cpu_per_seal[lo]:.2f}ms → {hi}w {cpu_per_seal[hi]:.2f}ms "
            f"per seal, ratio {ratio:.2f}) — the descriptor path "
            "degraded to copying"
        )
        # descriptor messages are tiny: per-worker wire cost must be
        # O(1) in blob bytes (way under 1% of the ~2.6MB of blobs)
        assert (
            out[f"bus_fanout_wire_bytes_per_worker_per_seal_{hi}w"]
            < per_seal_blob_bytes // 100
        ), "ring-mode seal messages are carrying blob-scale bytes"
    return out


def bench_edge_fanout(edge_counts=(1, 4), seals=24, subscribers=64) -> dict:
    """ISSUE 16 tentpole: compose-host cost vs EDGE count over the TCP
    frame bus, at a fixed total subscriber population.

    One in-process BusPublisher listens on TCP; N edge mirrors (REAL
    subprocesses — their drain CPU cannot pollute the compose
    measurement) each carry ``subscribers // N`` local readers off
    their mirror windows, so the viewer population never touches the
    compose host by construction.  Each tick the compose does the REAL
    per-tick work — build the seal blobs (JSON + gzip, the dominant
    cost of a live tick) — and publishes.  Reported per N: compose
    CPU per tick and bus egress bytes per EDGE per seal.

    Hard guards:

    - compose CPU per tick at 4 edges within 1.3x of 1 edge — the
      shared-body variant encoding (seal_wire_variant) makes the
      marginal edge a tiny header + one kernel send over the SAME
      body, so fan-out must never re-encode per edge;
    - egress bytes per edge per seal at 4 edges within 1.3x of 1 edge —
      per-link egress is the physically flat quantity (each replica
      necessarily receives one body; what must NOT happen is per-link
      inflation from re-encoding, snapshot churn, or resyncs);
    - a bad-token edge hello is refused with an error message and the
      connection closed BEFORE any snapshot byte (no template, seal,
      or binding ever crosses an unauthenticated link).
    """
    import asyncio
    import json as _json
    import socket as _socket
    import subprocess
    import sys
    import tempfile

    from tpudash.broadcast.bus import (
        PROTO,
        BusPublisher,
        encode_message,
        read_message,
    )
    from tpudash.broadcast.cohort import CohortHub, Seal, compress_segment
    from tpudash.app.state import SelectionState

    token = "bench-edge-token"
    n_chips = 4096

    def build_blobs(seq: int) -> dict:
        # a live tick's dominant CPU: render the JSON body + gzip it
        chips = [
            {"id": f"slice-0/{i}", "util": (seq * 7 + i) % 100}
            for i in range(n_chips)
        ]
        full = _json.dumps({"seq": seq, "kind": "full", "chips": chips})
        full_b = full.encode()
        delta_b = full_b[: len(full_b) // 3]
        return {
            "sse_full_raw": full_b,
            "sse_full_gz": compress_segment(full_b),
            "sse_delta_raw": delta_b,
            "sse_delta_gz": compress_segment(delta_b),
            "frame_raw": full_b,
            "frame_gz": compress_segment(full_b),
        }

    reader_src = (
        "import asyncio, sys\n"
        "from tpudash.broadcast.bus import BusMirror\n"
        "async def main():\n"
        "    addr, tok = sys.argv[1], sys.argv[2]\n"
        "    idx, subs = int(sys.argv[3]), int(sys.argv[4])\n"
        "    m = BusMirror('', pid=0, index=idx, connect=addr,\n"
        "                  token=tok, role='edge')\n"
        "    stop = asyncio.Event()\n"
        "    asyncio.ensure_future(m.run(stop))\n"
        "    async def subscriber():\n"
        "        seen = 0\n"
        "        while True:\n"
        "            for w in list(m.windows.values()):\n"
        "                s = w.latest()\n"
        "                if s is not None:\n"
        "                    seen ^= len(s.sse_full_raw)\n"
        "            await asyncio.sleep(0.05)\n"
        "    for _ in range(subs):\n"
        "        asyncio.ensure_future(subscriber())\n"
        "    await asyncio.Event().wait()\n"
        "asyncio.run(main())\n"
    )

    probe = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    listen = f"127.0.0.1:{port}"

    out: dict = {}
    cpu_per_tick: dict = {}
    egress_per_edge: dict = {}
    for edges in edge_counts:
        async def run_one(edges=edges):
            hub = CohortHub(lambda s: {}, _json.dumps, window=4)
            state = SelectionState()
            state.selected = ["bench"]
            cohort = hub.resolve(state)
            pub = BusPublisher(
                None, hub, backlog=256, listen=listen, token=token
            )
            await pub.start()
            procs = [
                subprocess.Popen(
                    [
                        sys.executable,
                        "-c",
                        reader_src,
                        listen,
                        token,
                        str(i),
                        str(max(1, subscribers // edges)),
                    ],
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                )
                for i in range(edges)
            ]
            try:
                for _ in range(400):
                    if len(pub.workers()) >= edges:
                        break
                    await asyncio.sleep(0.05)
                assert len(pub.workers()) >= edges, "edges connected"
                # unmeasured warm-up ticks: gzip tables, allocator
                # arenas, and the first template send all land here,
                # not in either measured leg
                for seq in range(1, 4):
                    pub.publish_seal(
                        Seal(
                            cohort.cid, seq, (seq, False),
                            *build_blobs(seq).values(),
                        )
                    )
                    await asyncio.sleep(0.01)
                # exclude connect/snapshot/warm-up traffic from the
                # egress measurement: count from here
                egress0 = (
                    pub.counters["blob_bytes_published"]
                    + pub.counters["desc_bytes_published"]
                )
                published = 0
                c0 = time.process_time()
                for seq in range(4, seals + 4):
                    seal = Seal(
                        cohort.cid, seq, (seq, False),
                        *build_blobs(seq).values(),
                    )
                    pub.publish_seal(seal)
                    published += 1
                    # let the drains run; the backlog (256) is far above
                    # the burst (24 seals), so no pacing poll is needed —
                    # a poll would itself cost CPU proportional to the
                    # connection count and pollute the flatness ratio
                    await asyncio.sleep(0.003)
                for _ in range(400):
                    ws = pub.workers()
                    if ws and all(
                        w["queued"] == 0 and w["sent"] >= published
                        for w in ws
                    ):
                        break
                    await asyncio.sleep(0.025)
                cpu_ms = (time.process_time() - c0) * 1e3
                egress = (
                    pub.counters["blob_bytes_published"]
                    + pub.counters["desc_bytes_published"]
                    - egress0
                )
                st = pub.stats()
                resyncs = sum(
                    (w.get("health") or {}).get("resyncs", 0)
                    for w in st["workers"]
                )
                return {
                    "cpu_ms_per_tick": cpu_ms / published,
                    "egress_per_edge_per_seal": egress
                    / (edges * published),
                    "cuts": sum(st["cuts"].values()),
                    "resyncs": resyncs,
                    "published": published,
                }
            finally:
                for p in procs:
                    p.kill()
                for p in procs:
                    p.wait()
                await pub.close()

        r = asyncio.run(run_one())
        cpu_per_tick[edges] = r["cpu_ms_per_tick"]
        egress_per_edge[edges] = r["egress_per_edge_per_seal"]
        out[f"edge_fanout_cpu_ms_per_tick_{edges}e"] = round(
            r["cpu_ms_per_tick"], 3
        )
        out[f"edge_fanout_egress_bytes_per_edge_per_seal_{edges}e"] = int(
            r["egress_per_edge_per_seal"]
        )
        # a healthy-bench sanity floor: no cut or resync may have
        # inflated (or hidden) the measured egress
        assert r["cuts"] == 0 and r["resyncs"] == 0, (
            f"bench links were not healthy: {r['cuts']} cuts, "
            f"{r['resyncs']} resyncs"
        )
    lo, hi = min(edge_counts), max(edge_counts)
    cpu_ratio = cpu_per_tick[hi] / max(cpu_per_tick[lo], 1e-9)
    egress_ratio = egress_per_edge[hi] / max(egress_per_edge[lo], 1e-9)
    out["edge_fanout_cpu_flat_ratio"] = round(cpu_ratio, 2)
    out["edge_fanout_egress_flat_ratio"] = round(egress_ratio, 2)
    assert cpu_ratio <= 1.3, (
        f"compose CPU per tick scaled with edge count ({lo}e "
        f"{cpu_per_tick[lo]:.2f}ms → {hi}e {cpu_per_tick[hi]:.2f}ms, "
        f"ratio {cpu_ratio:.2f} > 1.3) — the shared-body variant "
        "encoding degraded to per-edge re-encodes"
    )
    assert egress_ratio <= 1.3, (
        f"bus egress per edge grew with edge count (ratio "
        f"{egress_ratio:.2f} > 1.3) — per-link inflation from "
        "re-encoding, snapshot churn, or resyncs"
    )

    # -- bad-token hello: refused before any snapshot byte -------------------
    async def bad_token():
        hub = CohortHub(lambda s: {}, _json.dumps, window=4)
        state = SelectionState()
        state.selected = ["bench"]
        cohort = hub.resolve(state)
        cohort.window.append(
            Seal(
                cohort.cid, 1, (1, False),
                *build_blobs(1).values(),
            )
        )
        pub = BusPublisher(
            None, hub, backlog=256, listen=listen, token=token
        )
        await pub.start()
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(
                encode_message(
                    {
                        "t": "hello",
                        "pid": 0,
                        "index": 0,
                        "role": "edge",
                        "proto": PROTO,
                        "token": "wrong-token",
                    }
                )
            )
            await writer.drain()
            kinds = []
            try:
                while True:
                    head, _blobs = await asyncio.wait_for(
                        read_message(reader), 10.0
                    )
                    kinds.append(head.get("t"))
            except (asyncio.IncompleteReadError, ConnectionError):
                pass  # publisher closed the link — the expected end
            writer.close()
            assert kinds and kinds[0] == "error", (
                f"bad-token hello was not refused with an error: {kinds}"
            )
            assert not any(
                k in ("snapshot", "seal", "template", "binding")
                for k in kinds
            ), f"unauthenticated edge received bus content: {kinds}"
            assert pub.counters["auth_rejects"] >= 1
            assert pub.workers() == [], "refused edge still holds a slot"
        finally:
            await pub.close()

    asyncio.run(bad_token())
    out["edge_fanout_bad_token_refused"] = True
    return out


def bench_sse_subscribers(counts=(1, 8, 32, 256, 1024), ticks=8) -> dict:
    """N concurrent gzip SSE subscribers at 256 chips over the REAL
    stream handler (VERDICT r4 #6 — the "dashboard on every SRE's wall"
    scenario).  All N share one *cohort* (same selection, same style):
    the hub composes, delta-encodes, and gzips ONCE per tick into a
    sealed buffer, so the per-subscriber cost is a pure buffer write
    (tpudash.broadcast — the BENCH_r05 serving wall this subsystem
    removes; the pre-cohort curve grew ~1.3 ms CPU/tick per client).

    Reported per N: the whole-process CPU cost of one steady-state tick
    with all N subscribers attached (process CPU time / ticks, measured
    from a barrier AFTER every subscriber received its one-off full
    frame — wall time is sleep-paced by the SSE loop and would only
    measure the pacing), plus the cohort hub's own executor-side
    compose+encode cost for the same window
    (``sse_subscribers_{n}_cohort_ms_per_tick``) — THE per-cohort
    number, measured inside the hub and independent of fan-out width.
    Server and subscribers share the process, so the whole-process
    number includes each client's gzip decode and buffer splitting — a
    term that scales LINEARLY with N, which makes the reported
    sublinearity a conservative upper bound on the server's own fan-out
    cost.  Also reported: steady-state wire bytes per subscriber per
    tick (counted after the full frame) and resident memory.

    Hard guards: ticks at the widest fan-out must stay deep inside the
    5 s refresh budget; per-subscriber wire cost must stay in the
    tens-of-KB band the single-subscriber bench established; and the
    compose-once contract itself — the per-cohort compose/delta/gzip
    cost at 256 subscribers must be flat vs 32 (per-client marginal
    ≤ 0.1 ms/tick), else a change quietly re-introduced per-subscriber
    compose work."""
    import asyncio
    import time as _t
    import zlib

    from aiohttp import ClientSession
    from aiohttp.test_utils import TestServer

    from tpudash.app.server import DashboardServer

    out = {}
    cohort_ms = {}
    cpu_anchor = None
    for n in counts:
        # refresh_interval matches the stream loop's 0.25 s sleep floor
        # (server.stream pacing): a smaller value would re-scrape inside
        # one tick cluster whenever subscriber wakeups smear past it,
        # billing phantom scrapes to the fan-out being measured;
        # max_streams lifted above the widest fan-out — shedding is
        # bench_shed_latency's subject, not this one's
        svc = _bench_service(
            N_CHIPS, refresh_interval=0.25, max_streams=2 * max(counts)
        )
        server = DashboardServer(svc)
        steady_bytes = [0]
        hub_marks = {}

        async def run(n=n):
            ts = TestServer(server.build_app())
            await ts.start_server()
            url = ts.make_url("/api/stream")
            warm = [asyncio.Event() for _ in range(n)]
            steady = asyncio.Event()
            marks = {}

            async def subscribe(session, i):
                d = zlib.decompressobj(16 + zlib.MAX_WBITS)
                steady_events = 0
                async with session.get(
                    url, headers={"Accept-Encoding": "gzip"}
                ) as r:
                    assert r.headers.get("Content-Encoding") == "gzip"
                    buf = b""
                    async for chunk in r.content.iter_any():
                        if steady.is_set():
                            # window-based wire accounting: everything a
                            # subscriber receives in steady state counts
                            # (keepalive comments included — they ARE a
                            # tick's wire cost), the one-off full frame
                            # does not (priced by sse_full_frame_bytes)
                            steady_bytes[0] += len(chunk)
                        buf += d.decompress(chunk)
                        while b"\n\n" in buf:
                            evt, buf = buf.split(b"\n\n", 1)
                            if not warm[i].is_set():
                                if not evt.startswith(b":"):
                                    warm[i].set()  # baseline full frame
                                continue
                            if steady.is_set():
                                steady_events += 1
                        if steady_events >= ticks:
                            return

            async def mark_when_warm():
                # barrier: the N full-frame serializations are setup,
                # not tick cost — start the clocks (and the byte window)
                # once every subscriber holds its baseline frame
                for e in warm:
                    await e.wait()
                marks["cpu0"] = _t.process_time()
                marks["t0"] = _t.perf_counter()
                hub_marks["ms0"] = (
                    server.hub.compose_ms_total + server.hub.encode_ms_total
                )
                hub_marks["seals0"] = server.hub.counters["seals"]
                steady.set()

            # auto_decompress off: we count the gzip bytes on the wire;
            # unbounded pool + no per-request timeout — 1024 concurrent
            # streams are the subject, the client connector must not be
            # the limiter
            from aiohttp import ClientTimeout, TCPConnector

            async with ClientSession(
                auto_decompress=False,
                connector=TCPConnector(limit=0),
                timeout=ClientTimeout(total=None),
            ) as session:
                await asyncio.gather(
                    mark_when_warm(),
                    *[subscribe(session, i) for i in range(n)],
                )
                cpu_s = _t.process_time() - marks["cpu0"]
                wall_s = _t.perf_counter() - marks["t0"]
                hub_ms = (
                    server.hub.compose_ms_total
                    + server.hub.encode_ms_total
                    - hub_marks["ms0"]
                )
                seals = server.hub.counters["seals"] - hub_marks["seals0"]
            await ts.close()
            return cpu_s, wall_s, hub_ms, seals

        cpu_s, wall_s, hub_ms, seals = asyncio.run(run())
        per_sub_tick = steady_bytes[0] / (n * ticks)
        cpu_tick_ms = 1e3 * cpu_s / ticks
        # the cohort's own compose+delta+gzip cost per data tick — every
        # steady-state seal was one tick's worth of shared work
        cohort_ms[n] = hub_ms / max(1, seals)
        # boundedness: a full tick fanned out to N subscribers must stay
        # deep inside the refresh budget, and wire cost per subscriber
        # must not balloon with fan-out (shared-delta contract).  Above
        # the historical 32-count anchor the whole-process number is
        # dominated by the IN-PROCESS clients' own decode + scheduling
        # (server and 1024 subscribers share one interpreter), so the
        # guard there is marginal: ≤5 ms of combined client+server CPU
        # per extra subscriber per tick — an order of magnitude under
        # the pre-cohort curve once the client share is subtracted
        if n <= 32:
            assert cpu_tick_ms / 1e3 < BUDGET_S / 5.0, (
                f"SSE tick at {n} subscribers costs {cpu_tick_ms:.0f}ms CPU"
            )
            cpu_anchor = (n, cpu_tick_ms)
        elif cpu_anchor is not None:
            anchor_n, anchor_ms = cpu_anchor
            marginal_all = (cpu_tick_ms - anchor_ms) / (n - anchor_n)
            assert marginal_all <= 5.0, (
                f"SSE fan-out cost blew up: {cpu_tick_ms:.0f}ms CPU/tick "
                f"at {n} subscribers ({marginal_all:.2f}ms marginal per "
                f"client incl. in-process client decode)"
            )
        else:
            # custom wide-only counts (no ≤32 anchor ran): absolute
            # bound — a tick must still fit the refresh budget
            assert cpu_tick_ms / 1e3 < BUDGET_S, (
                f"SSE tick at {n} subscribers costs {cpu_tick_ms:.0f}ms CPU"
            )
        assert per_sub_tick < 65536, (
            f"steady SSE tick {per_sub_tick:.0f}B/sub at {n} subscribers"
        )
        out[f"sse_subscribers_{n}_cpu_ms_per_tick"] = round(cpu_tick_ms, 2)
        out[f"sse_subscribers_{n}_cohort_ms_per_tick"] = round(
            cohort_ms[n], 3
        )
        out[f"sse_subscribers_{n}_wire_bytes_per_sub_tick"] = round(
            per_sub_tick
        )
        out[f"sse_subscribers_{n}_wall_s"] = round(wall_s, 2)
    # compose-once regression guard (ISSUE 6 acceptance): the per-cohort
    # cost must NOT scale with fan-out width.  Marginal per-client cost
    # within the cohort, 32 → 256 subscribers, capped at 0.1 ms/tick —
    # the pre-cohort design sat at ~1.3 ms/client and would fail this by
    # an order of magnitude.
    if 32 in cohort_ms and 256 in cohort_ms:
        marginal = (cohort_ms[256] - cohort_ms[32]) / (256 - 32)
        out["sse_cohort_marginal_cpu_ms_per_client"] = round(marginal, 4)
        assert marginal <= 0.1, (
            f"per-cohort compose cost is no longer flat: "
            f"{cohort_ms[32]:.2f}ms/tick at 32 subs vs "
            f"{cohort_ms[256]:.2f}ms/tick at 256 "
            f"({marginal:.3f}ms marginal per client)"
        )
    out["sse_subscribers_rss_mb"] = _rss_mb()
    return out


def bench_shed_latency(samples: int = 40) -> dict:
    """The overload fast paths, priced (ISSUE 3): time-to-503 for a shed
    request under a saturated concurrency gate, and the stale-frame serve
    time for a shed ``GET /api/frame``.  Both paths exist so the server
    stays cheap at any request rate — if a future change drags locks or
    executor hops into them, these numbers move and the regression guard
    sees it.  Saturation is imposed directly on the admission guard
    (inflight pinned at the gate) so the measurement is of the shed path
    itself, not of a racing load generator."""
    import asyncio
    import statistics

    from aiohttp import ClientSession
    from aiohttp.test_utils import TestServer

    from tpudash.app.server import DashboardServer

    svc = _bench_service(N_CHIPS, refresh_interval=60.0, max_concurrency=4)
    server = DashboardServer(svc)

    async def run():
        ts = TestServer(server.build_app())
        await ts.start_server()
        try:
            async with ClientSession() as session:
                # one admitted frame so the degraded path has data
                async with session.get(ts.make_url("/api/frame")) as r:
                    assert r.status == 200
                server.overload.inflight = server.overload.max_concurrency
                shed_ms, stale_ms = [], []
                for _ in range(samples):
                    t0 = time.perf_counter()
                    async with session.get(ts.make_url("/api/timings")) as r:
                        assert r.status == 503
                        assert r.headers.get("Retry-After")
                        await r.read()
                    shed_ms.append((time.perf_counter() - t0) * 1e3)
                    t0 = time.perf_counter()
                    async with session.get(ts.make_url("/api/frame")) as r:
                        assert r.status == 200
                        body = await r.json()
                        assert body["stale"] is True
                    stale_ms.append((time.perf_counter() - t0) * 1e3)
                server.overload.inflight = 0
                return (
                    statistics.median(shed_ms), statistics.median(stale_ms)
                )
        finally:
            await ts.close()

    shed_p50, stale_p50 = asyncio.run(run())
    # boundedness: shedding must stay far cheaper than serving — a shed
    # path that grew a lock wait or an executor hop defeats its purpose
    assert shed_p50 < 250.0, f"time-to-503 p50 {shed_p50:.1f}ms"
    assert stale_p50 < 1000.0, f"stale-frame serve p50 {stale_p50:.1f}ms"
    return {
        "shed_503_p50_ms": round(shed_p50, 2),
        "stale_frame_p50_ms": round(stale_p50, 2),
    }


_PROBE_SNIPPET = """
import json
import statistics
try:
    from tpudash.ops.probes import (
        device_info, hbm_bandwidth_probe, hbm_copy_probe, matmul_flops_probe,
    )
    from tpudash.registry import resolve_generation_from_device_kind
    info = device_info()
    if info["platform"] not in ("tpu",):
        print(json.dumps({"platform": info["platform"]}))
    else:
        # median-of-5 with wide windows: single-shot numbers drifted ~2%
        # round to round, and short windows let tunneled-dispatch jitter
        # swing a measurement past the datasheet peak (a 105% "MFU" is a
        # measurement artifact, not a miracle)
        med = lambda fn: statistics.median(fn().value for _ in range(5))
        mm = med(lambda: matmul_flops_probe(size=4096, iters=64))
        hbm = med(lambda: hbm_bandwidth_probe(mb=256, k1=10, k2=410))
        cp = med(lambda: hbm_copy_probe(mb=256, k1=5, k2=205))
        out = {
            "platform": info["platform"],
            "device_kind": info["device_kind"],
            "probe_repeats": 5,
            "matmul_bf16_tflops": round(mm, 2),
            "hbm_stream_gbps": round(hbm, 1),
            "hbm_copy_gbps": round(cp, 1),
        }
        gen = resolve_generation_from_device_kind(info["device_kind"])
        if gen is not None:
            # achieved fraction of the datasheet ceilings the dashboard
            # itself gauges against (registry.py) — the honest MFU number
            out["generation"] = gen.name
            out["matmul_mfu_pct"] = round(100.0 * mm / gen.peak_bf16_tflops, 1)
            out["hbm_stream_pct_of_peak"] = round(100.0 * hbm / gen.hbm_gbps, 1)
        print(json.dumps(out))
except Exception as e:
    print(json.dumps({"probe_error": str(e)}))
"""


def bench_tsdb(n_frames: int = 600, n_chips: int = 64, n_cols: int = 6) -> dict:
    """The embedded trend store (tpudash.tsdb): ingest throughput,
    achieved compression vs the raw JSON history representation it
    replaced, and range-query p50 over the full horizon.

    Frames are realistic monitoring data — per-chip utilization drifts
    with noise, power steps, near-constant ratios, all quantized the way
    normalize emits them — at the 5 s cadence.  The JSON baseline is the
    exact ``/api/history`` wire shape (per-point column-keyed dicts),
    i.e. what shipping the same horizon from the legacy deque tier
    costs.  Hard floor: the ratio asserts ≥ 5× (the ISSUE 5 acceptance
    bar); the regression guard watches all three numbers across rounds.
    """
    import numpy as np

    from tpudash.tsdb import FLEET_SERIES, TSDB
    from tpudash.tsdb.query import range_query

    rng = np.random.default_rng(5)
    keys = [f"slice-0/{i}" for i in range(n_chips)] + [FLEET_SERIES]
    cols = [f"metric_{i}" for i in range(n_cols)]
    base = time.time() - n_frames * 5.0
    # fabricate OUTSIDE the timed window (payload assembly is not the
    # store's cost, same rule as the frame benches)
    walk = rng.normal(0, 0.4, size=(n_frames, len(keys), n_cols))
    level = rng.uniform(40.0, 90.0, size=(len(keys), n_cols))
    mats = [
        np.round(level + np.cumsum(walk, axis=0)[i], 1).astype(np.float32)
        for i in range(n_frames)
    ]
    stamps = [base + 5.0 * i for i in range(n_frames)]
    store = TSDB(chunk_points=120)
    t0 = time.perf_counter()
    for ts, mat in zip(stamps, mats):
        store.append_frame(ts, keys, cols, mat)
    store.flush(seal_partial=True)  # sealing is part of the ingest cost
    ingest_s = time.perf_counter() - t0
    stats = store.stats()
    n_points = n_frames * len(keys) * n_cols
    # native-vs-Python codec throughput, side by side (ISSUE 10): same
    # frames through a store whose Gorilla encode is pinned to the pure-
    # Python path — the two columns quantify what the native hot loop
    # buys, and the ratio regressing means the native path quietly
    # stopped engaging
    from tpudash.tsdb import gorilla as _g

    native_encoders = (_g.encode_timestamps, _g.encode_values)
    try:
        _g.encode_timestamps = _g.encode_timestamps_py
        _g.encode_values = _g.encode_values_py
        store_py = TSDB(chunk_points=120)
        t0 = time.perf_counter()
        for ts, mat in zip(stamps, mats):
            store_py.append_frame(ts, keys, cols, mat)
        store_py.flush(seal_partial=True)
        ingest_py_s = time.perf_counter() - t0
    finally:
        _g.encode_timestamps, _g.encode_values = native_encoders
    assert stats["raw_points"] == n_frames, "bench store lost frames"
    # baseline: the same horizon in the legacy /api/history JSON shape
    json_bytes = len(
        _dumps(
            [
                {
                    "ts": ts,
                    "values": {c: float(mat[0, j]) for j, c in enumerate(cols)},
                }
                for ts, mat in zip(stamps, mats)
            ]
        ).encode()
    ) * len(keys)
    ratio = json_bytes / max(1, stats["compressed_bytes"])
    assert ratio >= 5.0, f"tsdb compression ratio {ratio:.1f}x < 5x"
    # range-query p50: one chip, one column, full horizon, default budget
    q_times = []
    for i in range(30):
        key = keys[i % n_chips]
        t0 = time.perf_counter()
        res = range_query(store, key, cols=[cols[0]], start_s=base)
        q_times.append(time.perf_counter() - t0)
        assert res["series"][cols[0]], "range query returned no points"
    q_times.sort()
    return {
        "tsdb_ingest_points_per_s": int(n_points / ingest_s),
        "tsdb_ingest_mpoints_per_s": round(n_points / ingest_s / 1e6, 3),
        "tsdb_ingest_mpoints_per_s_py": round(
            n_points / ingest_py_s / 1e6, 3
        ),
        "tsdb_ingest_frames_per_s": round(n_frames / ingest_s, 1),
        "tsdb_compression_ratio": round(ratio, 1),
        "tsdb_compressed_bytes": stats["compressed_bytes"],
        "tsdb_range_p50_ms": round(q_times[len(q_times) // 2] * 1e3, 2),
    }


def bench_snapshot(n_frames: int = 600, n_chips: int = 64, n_cols: int = 6) -> dict:
    """Online snapshots (tpudash.tsdb.snapshot): snapshot duration vs
    store size, and — the contract that makes them "online" — the
    ingest stall while one runs.  An appender thread hammers
    ``append_frame`` the whole time a snapshot is taken; the longest
    inter-append gap is the stall.  The head cut is a pointer swap and
    the link/CRC work happens off the ingest path, so the guard is a
    hard sub-250 ms ceiling (generous for a noisy CI host; the typical
    number is single-digit ms), and a follower catch-up case measures
    the standby's replay rate over the same segment set."""
    import os
    import shutil
    import tempfile
    import threading

    import numpy as np

    from tpudash.tsdb import FLEET_SERIES, TSDB
    from tpudash.tsdb.follower import FollowerTSDB
    from tpudash.tsdb.snapshot import take_snapshot

    work = tempfile.mkdtemp(prefix="tpudash-bench-snap-")
    try:
        store_dir = os.path.join(work, "store")
        store = TSDB(path=store_dir, chunk_points=240)
        rng = np.random.default_rng(9)
        keys = [f"slice-0/{i}" for i in range(n_chips)] + [FLEET_SERIES]
        cols = [f"metric_{i}" for i in range(n_cols)]
        base = time.time() - n_frames * 5.0
        mats = [
            np.round(
                rng.uniform(20.0, 90.0, size=(len(keys), n_cols)), 1
            ).astype(np.float32)
            for _ in range(8)
        ]
        for i in range(n_frames):
            store.append_frame(base + 5.0 * i, keys, cols, mats[i % 8])
        store.flush(seal_partial=True)
        snapped_bytes = store.stats()["compressed_bytes"]

        stop = threading.Event()
        gaps: "list[float]" = []

        def appender():
            # ~500 appends/s: far hotter than any real refresh cadence,
            # but throttled enough that head cuts stay rarer than the
            # seal drain (an unthrottled spin would just starve the
            # inline flush and measure its own backlog, not the stall)
            i = n_frames
            last = time.perf_counter()
            while not stop.is_set():
                store.append_frame(
                    base + 5.0 * i, keys, cols, mats[i % 8]
                )
                now = time.perf_counter()
                gaps.append(now - last)
                last = now
                i += 1
                time.sleep(0.002)

        t = threading.Thread(target=appender, daemon=True)
        t.start()
        time.sleep(0.05)  # let the appender reach steady state
        t0 = time.perf_counter()
        snap = take_snapshot(store, os.path.join(work, "snaps"))
        snap_s = time.perf_counter() - t0
        time.sleep(0.05)
        stop.set()
        t.join(timeout=5.0)
        stall_ms = max(gaps) * 1e3 if gaps else 0.0
        assert stall_ms < 250.0, (
            f"snapshot stalled ingest {stall_ms:.1f}ms — the head cut "
            "must stay a pointer swap"
        )
        # follower catch-up: replay the sealed segment set cold
        t0 = time.perf_counter()
        follower = FollowerTSDB(store_dir, poll_interval_s=60.0)
        catchup_s = time.perf_counter() - t0
        pts = follower.stats()["raw_points"]
        follower.close()
        assert pts > 0, "follower applied nothing from the bench store"
        return {
            "snapshot_ms": round(snap_s * 1e3, 2),
            "snapshot_bytes": snap["bytes"],
            "snapshot_files": snap["files"],
            "snapshot_store_compressed_bytes": snapped_bytes,
            "snapshot_ingest_stall_ms": round(stall_ms, 3),
            "follower_catchup_points_per_s": int(
                pts * len(keys) * n_cols / max(1e-9, catchup_s)
            ),
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


def bench_anomaly_scoring(counts=(1024, 4096), ticks: int = 30) -> dict:
    """The anomaly engine's per-tick scoring-hook cost at fleet scale
    (ISSUE 12): full ``AnomalyEngine.observe`` — baseline ingest +
    batch z-scoring + fabric correlation — over a real parsed frame at
    1024/4096 chips, numpy and jax paths side by side.

    The hook rides the hard-gated publish path, so it carries its own
    hard bar: the 4096-chip numpy p50 must stay under 10% of
    ``SCALE_4096_P50_BUDGET_MS`` — detection must never buy back the
    frame budget PR 9 earned.  The jax number is reported for the
    fleet-scale (100k+ federated chips) story; on small hosts numpy
    wins and that is expected."""
    import statistics

    from tpudash.anomaly.detect import AnomalyEngine
    from tpudash.config import Config
    from tpudash.normalize import dense_block, to_wide
    from tpudash.sources.base import parse_instant_query
    from tpudash.sources.fixture import synthetic_payload
    from tpudash.stragglers import DEFAULT_DIRECTIONS

    out: dict = {}
    for n in counts:
        payload = synthetic_payload(num_chips=n, emit_links=True, t=1000.0)
        df = to_wide(parse_instant_query(payload))
        block = dense_block(df)
        keys = df.index.tolist()
        for suffix, use_jax in (("", False), ("_jax", True)):
            key_name = f"anomaly_score_{n}{suffix}_p50_ms"
            eng = AnomalyEngine.from_config(
                Config(anomaly=True, anomaly_jax=use_jax)
            )
            if use_jax and eng.backend != "jax":
                out[key_name] = None  # jax unavailable — reported, not hidden
                continue
            # warm the seasonal baselines (MIN_COUNT folds) so scoring
            # runs the real warm path, not the all-NaN cold path
            wcols, x = eng._values(df, block, sorted(DEFAULT_DIRECTIONS))
            for m in range(7):
                eng.baselines.ingest(580.0 + 60.0 * m, keys, wcols, x)
            times = []
            for t in range(ticks):
                t0 = time.perf_counter()
                eng.observe(
                    1000.0 + 5.0 * t, df, block=block, stragglers=[], keys=keys
                )
                times.append(time.perf_counter() - t0)
            out[key_name] = round(statistics.median(times) * 1e3, 3)
    budget_ms = 0.10 * SCALE_4096_P50_BUDGET_MS
    measured = out.get("anomaly_score_4096_p50_ms")
    assert measured is not None and measured <= budget_ms, (
        f"anomaly scoring hook costs {measured} ms at 4096 chips — over "
        f"10% of the hard-gated {SCALE_4096_P50_BUDGET_MS} ms frame "
        f"budget ({budget_ms:.1f} ms)"
    )
    return out


def bench_federation(
    child_counts=(2, 8, 16), frames: int = 12, chips_per_child: int = 256
) -> dict:
    """The federation parent's fan-in cost: scrape→render p50 of a fleet
    frame vs child count (ISSUE 9 — the path that turns the 4096-chip
    single-process wall into an N×child aggregation problem).

    Children are in-memory summary clients replaying ONE real child's
    serialized ``/api/summary`` document (produced by a live 256-chip
    service, so the wire shape is exactly production's), each poll
    returning a freshly-decoded copy under a new ETag — the worst case:
    every child changed every tick, no 304s.  The measured number is
    therefore the PARENT's whole pipeline — summary JSON decode × N,
    batch union, normalize, alerts, compose — with child HTTP and child
    compose excluded, exactly as Prometheus's own response assembly is
    excluded from the frame benches.  16 × 256 = the 4,096-chip shape
    the single-process wall was measured at."""
    import json as _json

    from tpudash.app.service import DashboardService
    from tpudash.config import Config
    from tpudash.federation.client import SummaryResult
    from tpudash.federation.source import ChildSpec, FederatedSource

    from tpudash.app import wire

    child = _bench_service(chips_per_child)
    child.render_frame()
    blob = _dumps(child.summary_doc())
    bin_blob = wire.encode_summary(child.summary_doc(binary=True))

    class _ReplayClient:
        def __init__(self):
            self.v = 0

        def fetch(self, etag, timeout):
            self.v += 1
            return SummaryResult(doc=_json.loads(blob), etag=f"e{self.v}")

    class _ReplayClientBin:
        """The binary summary path a real HttpSummaryClient negotiates:
        each poll pays the TDB1 decode (one frombuffer for the matrix)
        instead of the JSON cell parse — the fan-in term ISSUE 10's
        federation ride-along shaves."""

        def __init__(self):
            self.v = 0

        def fetch(self, etag, timeout):
            self.v += 1
            return SummaryResult(
                doc=wire.decode_summary(bin_blob), etag=f"e{self.v}"
            )

    out = {}
    for n in child_counts:
        specs = [ChildSpec(f"c{i}", f"http://c{i}") for i in range(n)]
        cfg = Config(
            federate=",".join(f"{s.name}={s.url}" for s in specs),
            federate_hedge=0.0,  # in-memory children never need hedging
            refresh_interval=0.0,
            node_id="bench-parent",
        )
        src = FederatedSource(cfg, children=[(s, _ReplayClient()) for s in specs])
        svc = DashboardService(cfg, src)
        svc.render_frame()  # warm
        svc.state.select_all(svc.available)
        svc.timer.history.clear()
        for _ in range(frames):
            frame = svc.render_frame()
            assert frame["error"] is None
            assert len(frame["selected"]) == n * chips_per_child
            assert not frame.get("partial"), "healthy fan-in marked partial"
        p50 = svc.timer.percentile(0.5)
        # the whole point of the tier: a fleet frame must fit the budget
        assert p50 < BUDGET_S, (
            f"federated fan-in at {n} children blew the budget: {p50:.2f}s"
        )
        out[f"federation_fanin_{n}_p50_ms"] = round(p50 * 1e3, 2)
    # the binary summary fan-in at the widest shape (16 × 256 = the
    # 4,096-chip wall): same parent pipeline, TDB1 decode per child
    n = max(child_counts)
    specs = [ChildSpec(f"b{i}", f"http://b{i}") for i in range(n)]
    cfg = Config(
        federate=",".join(f"{s.name}={s.url}" for s in specs),
        federate_hedge=0.0,
        refresh_interval=0.0,
        node_id="bench-parent",
    )
    src = FederatedSource(
        cfg, children=[(s, _ReplayClientBin()) for s in specs]
    )
    svc = DashboardService(cfg, src)
    svc.render_frame()
    svc.state.select_all(svc.available)
    svc.timer.history.clear()
    for _ in range(frames):
        frame = svc.render_frame()
        assert frame["error"] is None
        assert len(frame["selected"]) == n * chips_per_child
    out[f"federation_fanin_{n}_bin_p50_ms"] = round(
        svc.timer.percentile(0.5) * 1e3, 2
    )
    return out


def bench_federation_tree(
    shapes=((16, 4), (64, 1)), leaf_chips: int = 1024, frames: int = 4
) -> dict:
    """Fleets-of-fleets fan-in (ISSUE 15): a 3-level tree at ≥64k
    aggregate chips, measured at the ROOT.

    Two shapes carry the same 65,536 chips and the SAME downstream
    compose work (64 × 1024-chip slices at the root): 16 children of
    4,096 vs 64 children of 1,024.  The only thing that differs is
    per-child fan-in overhead, so the hard guard — the 64-child p50 must
    stay within 2× of the 16-child p50 — is exactly "fan-in cost is
    sub-linear in child count" with the chip-bound work held constant.

    The incremental-summary gate rides along: one mid-tier tick's TDB1
    delta (changed-cell bitmap + qv cells) must be ≥3× smaller than the
    full JSON summary document — HARD, plus the binary-full ratio and
    the parent-side delta decode cost for the record."""
    import copy as _copy

    from tpudash.app import wire
    from tpudash.app.service import DashboardService
    from tpudash.config import Config
    from tpudash.federation.client import SummaryResult
    from tpudash.federation.source import ChildSpec, FederatedSource

    leaf = _bench_service(leaf_chips, node_id="bench-leaf")
    leaf.render_frame()
    leaf_doc0 = leaf.summary_doc(binary=True)
    leaf.render_frame()  # the replay source advances one tick
    leaf_doc1 = leaf.summary_doc(binary=True)

    class _DocClient:
        """Replays a decoded doc under a fresh ETag per poll (worst
        case: every child changed every tick, no 304s)."""

        def __init__(self, doc):
            self.doc = doc
            self.v = 0

        def fetch(self, etag, timeout):
            self.v += 1
            return SummaryResult(
                doc=_copy.deepcopy(self.doc), etag=f"e{self.v}"
            )

    def make_mid(n_leaves: int):
        specs = [ChildSpec(f"l{j}", f"http://l{j}") for j in range(n_leaves)]
        cfg = Config(
            federate=",".join(f"{s.name}={s.url}" for s in specs),
            federate_hedge=0.0,
            refresh_interval=0.0,
            node_id="bench-mid",
        )
        clients = [_DocClient(leaf_doc0) for _ in specs]
        src = FederatedSource(
            cfg, children=list(zip(specs, clients))
        )
        svc = DashboardService(cfg, src)
        svc.render_frame()
        return svc, clients

    out: dict = {}
    # -- the incremental-summary bytes gate (one mid-tier tick) --------------
    mid, mid_clients = make_mid(4)  # 4,096-chip mid-tier parent
    mid_doc0 = mid.summary_doc(binary=True)
    for c in mid_clients:
        c.doc = leaf_doc1
    mid.render_frame()
    mid_doc1 = mid.summary_doc(binary=True)
    full_json = len(_dumps(mid.summary_doc()).encode())
    full_bin = len(wire.encode_summary(mid_doc1))
    delta = wire.encode_summary_delta(mid_doc1, mid_doc0, '"e0"')
    t0 = time.perf_counter()
    for _ in range(3):
        wire.decode_summary_delta(delta, mid_doc0, '"e0"')
    decode_ms = (time.perf_counter() - t0) / 3 * 1e3
    out["summary_full_json_bytes"] = full_json
    out["summary_full_bin_bytes"] = full_bin
    out["summary_delta_bytes"] = len(delta)
    out["summary_delta_shrink"] = round(full_json / len(delta), 2)
    out["summary_delta_shrink_bin"] = round(full_bin / len(delta), 2)
    out["summary_delta_decode_ms"] = round(decode_ms, 2)
    # the acceptance bar: steady-state fan-in bytes ≥3× below the full doc
    assert out["summary_delta_shrink"] >= 3.0, (
        f"incremental summary only {out['summary_delta_shrink']}x smaller "
        f"than the full doc ({len(delta)}B vs {full_json}B) — the qv delta "
        "path degraded"
    )

    # -- root fan-in p50 at both 65,536-chip shapes --------------------------
    p50s: dict = {}
    class _BinClient:
        """Replays one encoded TDB1 summary; each poll pays the real
        decode (one frombuffer) under a fresh ETag."""

        def __init__(self, blob):
            self.blob = blob
            self.v = 0

        def fetch(self, etag, timeout):
            self.v += 1
            return SummaryResult(
                doc=wire.decode_summary(self.blob), etag=f"e{self.v}"
            )

    for n_children, leaves_per in shapes:
        svc, _clients = make_mid(leaves_per)
        blob = wire.encode_summary(svc.summary_doc(binary=True))
        specs = [
            ChildSpec(f"m{i}", f"http://m{i}") for i in range(n_children)
        ]
        cfg = Config(
            federate=",".join(f"{s.name}={s.url}" for s in specs),
            federate_hedge=0.0,
            refresh_interval=0.0,
            node_id="bench-root",
        )
        src = FederatedSource(
            cfg, children=[(s, _BinClient(blob)) for s in specs]
        )
        root = DashboardService(cfg, src)
        root.render_frame()  # warm
        root.state.select_all(root.available)
        root.timer.history.clear()
        chips = n_children * leaves_per * leaf_chips
        for _ in range(frames):
            frame = root.render_frame()
            assert frame["error"] is None
            assert len(frame["chips"]) == chips
            assert not frame.get("partial")
        p50 = root.timer.percentile(0.5)
        p50s[n_children] = p50
        out[
            f"federation_tree_{n_children}x{leaves_per * leaf_chips}_p50_ms"
        ] = round(p50 * 1e3, 2)
    # sub-linear-in-child-count, chips held constant: 4× the children
    # must cost < 2× the frame
    lo, hi = min(p50s), max(p50s)
    assert p50s[hi] <= 2.0 * p50s[lo] + 0.010, (
        f"fan-in p50 scaled with child count: {p50s[lo] * 1e3:.1f}ms at "
        f"{lo} children → {p50s[hi] * 1e3:.1f}ms at {hi} (same 64k chips)"
    )
    return out


def bench_range_quantiles(
    n_frames: int = 2880, n_chips: int = 128, n_cols: int = 4
) -> dict:
    """The analytics plane's headline gate (ISSUE 13): a fleet-wide p99
    range query answered from the sealed quantile sketches must be
    ≥10× faster than the raw-decode exact answer over the same window,
    and land inside the sketch's documented accuracy bound
    (RANK_ERROR_BOUND — the reported p99 sits between the exact values
    at ranks 0.99 ± 0.01).  Both are HARD bars: losing either means the
    sketch tier quietly stopped being the read path."""
    import numpy as np

    from tpudash.analytics.sketch import RANK_ERROR_BOUND
    from tpudash.tsdb import FLEET_SERIES, TSDB
    from tpudash.tsdb.query import range_query

    rng = np.random.default_rng(13)
    keys = [f"slice-{i // 64}/{i}" for i in range(n_chips)]
    cols = [f"metric_{i}" for i in range(n_cols)]
    base = time.time() - n_frames * 5.0
    level = rng.uniform(40.0, 90.0, size=(n_chips, n_cols))
    walk = np.cumsum(rng.normal(0, 0.3, size=(n_frames, n_chips, n_cols)), axis=0)
    mats = np.round(level + walk, 1).astype(np.float32)
    stamps = base + 5.0 * np.arange(n_frames)
    store = TSDB(chunk_points=120)
    for i in range(n_frames):
        store.append_frame(float(stamps[i]), keys, cols, mats[i])
    store.flush(seal_partial=True)
    step = 600.0
    col = cols[0]

    # sketch path: fleet-distribution p99 per 10m bucket
    times = []
    for _ in range(20):
        t0 = time.perf_counter()
        res = range_query(
            store, FLEET_SERIES, cols=[col], start_s=base, step_s=step,
            agg="p99",
        )
        times.append(time.perf_counter() - t0)
    times.sort()
    sketch_p50 = times[len(times) // 2]
    pts = res["series"][col]
    assert pts, "sketch p99 query returned no points"
    assert res["resolution"] in ("1m", "10m"), res["resolution"]

    # raw-decode exact: every chip's raw points per bucket, full sort
    def exact():
        out = {}
        for k in keys:
            for t, v in store.raw_window(
                k, col, int(base * 1000), int(stamps[-1] * 1000) + 1
            ):
                out.setdefault(int(t // (step * 1000)), []).append(v)
        return {
            b: np.sort(np.asarray(vals, dtype=np.float64))
            for b, vals in out.items()
        }

    t0 = time.perf_counter()
    exact_buckets = exact()
    raw_ms = (time.perf_counter() - t0)
    speedup = raw_ms / max(sketch_p50, 1e-9)
    assert speedup >= 10.0, (
        f"sketch p99 only {speedup:.1f}x faster than raw decode (<10x): "
        f"{sketch_p50 * 1e3:.2f}ms vs {raw_ms * 1e3:.2f}ms"
    )
    # accuracy: every reported bucket inside the documented rank window
    worst = 0.0
    for ts, v in pts:
        b = int(ts // step)
        sv = exact_buckets.get(b)
        if sv is None or sv.size < 100:
            continue
        lo = sv[max(0, int((0.99 - RANK_ERROR_BOUND) * sv.size) - 1)]
        hi = sv[min(sv.size - 1, int((0.99 + RANK_ERROR_BOUND) * sv.size))]
        assert lo <= v <= hi, (
            f"sketch p99 {v:.3f} outside documented bound "
            f"[{lo:.3f}, {hi:.3f}] for bucket {b}"
        )
        exact_v = float(np.quantile(sv, 0.99))
        worst = max(worst, abs(v - exact_v))
    return {
        "range_quantile_sketch_p50_ms": round(sketch_p50 * 1e3, 2),
        "range_quantile_raw_decode_ms": round(raw_ms * 1e3, 2),
        "range_quantile_speedup": round(speedup, 1),
        "range_quantile_worst_abs_err": round(worst, 3),
        "range_quantile_points": len(pts),
    }


def bench_federated_range(children: int = 16, rounds: int = 20) -> dict:
    """Scatter-gather fan-in cost at 16 children (ISSUE 13): one child's
    REAL serialized range-state document (built from a real store,
    JSON-round-tripped like the wire would) served by fake clients, so
    the number isolates the dispatch + validate + merge machinery the
    parent actually pays per fleet range query — worst case, no child
    failures."""
    import dataclasses as _dc

    import numpy as np

    from tpudash.analytics.executor import parse_state_doc, range_state
    from tpudash.config import load_config
    from tpudash.federation.source import ChildSpec, FederatedSource
    from tpudash.tsdb import TSDB

    rng = np.random.default_rng(7)
    keys = [f"slice-0/{i}" for i in range(256)]
    cols = [f"metric_{i}" for i in range(4)]
    base = time.time() - 3600.0
    store = TSDB(chunk_points=120)
    level = rng.uniform(40.0, 90.0, size=(256, 4))
    for i in range(720):
        store.append_frame(
            base + 5.0 * i, keys, cols,
            np.round(level + rng.normal(0, 0.5, size=(256, 4)), 1).astype(
                np.float32
            ),
        )
    store.flush(seal_partial=True)
    doc_bytes = _dumps(
        range_state(store, None, None, base, None, 600.0, "p99", 500)
    )

    class FakeRangeClient:
        def fetch(self, params, timeout):
            return parse_state_doc(json.loads(doc_bytes))

    cfg = _dc.replace(load_config({}), federate="unused")
    specs = [
        (ChildSpec(f"c{i}", f"http://child-{i}:8050"), object())
        for i in range(children)
    ]
    src = FederatedSource(cfg, children=specs)
    for name in list(src._range_clients):
        src._range_clients[name] = FakeRangeClient()
    times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        gathered = src.scatter_range({"agg": "p99", "start": base})
        times.append(time.perf_counter() - t0)
        assert len(gathered["states"]) == children
        assert not gathered["partial"]
    times.sort()
    from tpudash.analytics.executor import merge_states

    t0 = time.perf_counter()
    merged = merge_states(gathered["states"], "p99")
    merge_ms = (time.perf_counter() - t0) * 1e3
    assert merged["series"], "federated merge produced no series"
    return {
        f"federated_range_fanin_{children}_p50_ms": round(
            times[len(times) // 2] * 1e3, 2
        ),
        "federated_range_merge_ms": round(merge_ms, 2),
        "federated_range_state_bytes": len(doc_bytes),
    }


def bench_cold_range(
    days: int = 90, n_chips: int = 4096, bundles: int = 90
) -> dict:
    """The cold archive tier's headline gate (ISSUE 18): a 90-day
    fleet-wide p99 over 4096 chips answered from archive bundle SKETCH
    sections located via the manifest sparse index — under 1 s, with
    ZERO raw sections decoded (the tier's counters prove the read
    path; decoding 90 days of raw points for 4096 chips would be
    minutes).  Archives are synthesized at full shape — 12 960 10m
    buckets, one bundle per day, each bucket a real fleet-distribution
    digest built from 4096 chip samples — with the digest BYTES shared
    across buckets: the read path decodes every bucket independently
    either way, so the costs under test (manifest scan, digest-checked
    download, section parse, per-step sketch merge) are the real ones.
    Plus compaction throughput: a real Compactor folding a real sealed
    store into verified bundles, MB/s."""
    import contextlib
    import os
    import shutil
    import tempfile

    import numpy as np

    from tpudash.analytics.sketch import QuantileSketch
    from tpudash.tsdb import FLEET_SERIES, TSDB
    from tpudash.tsdb.cold import ColdTier, build_bundle
    from tpudash.tsdb.compact import Compactor
    from tpudash.tsdb.objstore import FilesystemStore
    from tpudash.tsdb.query import range_query
    from tpudash.tsdb.rollup import ALL_KEY, TIER_10M_MS, SketchBlock
    from tpudash.tsdb.store import _REC_SKETCH, _sketch_payload

    rng = np.random.default_rng(18)
    work = tempfile.mkdtemp(prefix="tpudash-bench-cold-")
    col = "tensorcore_utilization"
    cold = store = None
    try:
        obj = FilesystemStore(os.path.join(work, "obj"))
        end_ms = (int(time.time() * 1000) // TIER_10M_MS) * TIER_10M_MS
        t0_ms = end_ms - days * 86_400_000
        digest = QuantileSketch.from_values(
            rng.uniform(20.0, 98.0, size=n_chips), budget=64
        ).to_bytes()
        per_bundle = days * (86_400_000 // TIER_10M_MS) // bundles
        for i in range(bundles):
            b0 = t0_ms + i * per_bundle * TIER_10M_MS
            buckets = (
                np.arange(per_bundle, dtype=np.int64) * TIER_10M_MS + b0
            )
            blk = SketchBlock(
                TIER_10M_MS, buckets, [ALL_KEY], [col],
                [[[digest]] for _ in range(per_bundle)],
                int(buckets[0]), int(buckets[-1]) + TIER_10M_MS - 1,
            )
            payload = _sketch_payload(blk)
            data, _man = build_bundle(
                [(_REC_SKETCH, TIER_10M_MS, blk.src_t0, blk.src_t1,
                  payload)],
                [], blk.src_t1, [ALL_KEY], [col],
            )
            obj.put(
                f"bundles/bundle-{blk.src_t0}-{blk.src_t1}-bench.tdb",
                data,
            )
        cold = ColdTier(
            obj,
            cache_dir=os.path.join(work, "cache"),
            cache_max_bytes=1 << 30,
            refresh_interval_s=3600.0,
        )
        store = TSDB(chunk_points=120)
        store.attach_cold(cold)
        start_s, end_s = t0_ms / 1000.0, end_ms / 1000.0
        first = None
        times = []
        for _ in range(12):
            q0 = time.perf_counter()
            res = range_query(
                store, FLEET_SERIES, cols=[col], start_s=start_s,
                end_s=end_s, agg="p99",
            )
            dt = time.perf_counter() - q0
            if first is None:
                first = dt  # cold local cache: includes the downloads
            else:
                times.append(dt)
        times.sort()
        p50 = times[len(times) // 2]
        pts = res["series"][col]
        assert len(pts) >= 400, f"cold p99 returned {len(pts)} points"
        assert res["resolution"] == "10m", res["resolution"]
        raw_parsed = cold.counters["sections_parsed_raw"]
        assert raw_parsed == 0, (
            f"{raw_parsed} raw section(s) decoded — the 90-day quantile "
            "path stopped answering from the sketch index"
        )
        assert cold.counters["sections_parsed_sketch"] >= bundles, (
            "sketch sections were not actually read from the archives"
        )
        assert p50 < 1.0, (
            f"90-day cold fleet p99 took {p50 * 1e3:.0f}ms (>= 1s hard "
            "gate): the bundle sketch index is no longer the read path"
        )

        # compaction throughput: real store, real segments, real
        # read-back-verified uploads
        hot = os.path.join(work, "hot")
        base = time.time() - 3600.0
        comp_store = TSDB(path=hot, chunk_points=120)
        keys = [f"slice-{i // 64}/{i}" for i in range(64)]
        cols = [f"metric_{i}" for i in range(4)]
        level = rng.uniform(40.0, 90.0, size=(64, 4))
        for i in range(720):
            comp_store.append_frame(
                base + 5.0 * i, keys, cols,
                np.round(
                    level + rng.normal(0, 0.5, size=(64, 4)), 1
                ).astype(np.float32),
            )
        comp_store.flush(seal_partial=True)
        comp_store.close()
        cold2 = ColdTier(
            FilesystemStore(os.path.join(work, "obj2")),
            cache_dir=os.path.join(work, "cache2"),
        )
        comp = Compactor(
            source_dir=hot, cold=cold2, interval_s=3600.0,
            include_tail=True,
        )
        summary = comp.run_once()
        comp.close()
        cold2.close()
        assert summary["bundles_written"] >= 1 and not summary["gave_up"], (
            f"compaction bench staged nothing: {summary}"
        )
        mb = summary["bytes_uploaded"] / (1 << 20)
        mb_per_s = mb / max(summary["duration_ms"] / 1e3, 1e-9)
        return {
            "cold_range_90d_first_ms": round(first * 1e3, 1),
            "cold_range_90d_p50_ms": round(p50 * 1e3, 1),
            "cold_range_90d_points": len(pts),
            "cold_range_bundles": bundles,
            "cold_range_raw_sections_parsed": raw_parsed,
            "cold_compact_mb_per_s": round(mb_per_s, 1),
            "cold_compact_bundles": summary["bundles_written"],
        }
    finally:
        with contextlib.suppress(Exception):
            if store is not None:
                store.close()
        with contextlib.suppress(Exception):
            if cold is not None:
                cold.close()
        shutil.rmtree(work, ignore_errors=True)


def bench_probes(timeout_s: float = 300.0) -> dict:
    """On-chip probe numbers, isolated in a SUBPROCESS with a hard
    timeout: a wedged accelerator runtime (e.g. a tunneled chip whose
    lease is stuck — jax backend init then blocks forever, it does not
    raise) must cost this bench one probe section, never the headline
    scrape→render number.  Probe windows are publication-grade (~70 ms of
    traffic per delta) so tunneled dispatch jitter stays <15% of signal.
    """
    import os
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SNIPPET],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        line = proc.stdout.strip().splitlines()
        if not line:
            return {"probe_error": f"no output (rc={proc.returncode}): "
                                   f"{proc.stderr.strip()[-300:]}"}
        return json.loads(line[-1])
    except subprocess.TimeoutExpired:
        return {"probe_error": f"probe subprocess timed out after {timeout_s:g}s"}
    except Exception as e:  # bench must still report the headline number
        return {"probe_error": str(e)}


def find_regressions(
    result: dict, bench_dir: "str | None" = None
) -> "tuple[str | None, list[dict]]":
    """Compare this run against the newest committed ``BENCH_r*.json``.

    A dashboard whose whole purpose is catching silent per-chip
    degradation should not itself ship silent degradation: probe numbers
    dropping >5% or the headline p50 inflating >20% vs the previous round
    are reported in a ``regressions`` field (the driver wraps its record
    in {"parsed": ...}; bare JSON is accepted too)."""
    import glob
    import os

    here = bench_dir or os.path.dirname(os.path.abspath(__file__))
    files = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    if not files:
        return None, []
    try:
        with open(files[-1]) as f:
            prev = json.load(f)
        prev = prev.get("parsed", prev)
    except (OSError, ValueError):
        return os.path.basename(files[-1]), []
    out = []

    def check(name, now, before, worse_when="lower", tol=0.05):
        if not isinstance(now, (int, float)) or not isinstance(before, (int, float)):
            return
        if before <= 0:
            return
        change = (now - before) / before
        bad = change < -tol if worse_when == "lower" else change > tol
        if bad:
            out.append(
                {
                    "metric": name,
                    "prev": before,
                    "now": now,
                    "change_pct": round(100.0 * change, 1),
                }
            )

    # fleets-of-fleets (ISSUE 15): the incremental-summary shrink is
    # deterministic (10% band — a drop means the qv delta path degraded;
    # the hard ≥3× floor lives inside bench_federation_tree itself); the
    # 3-level fan-in p50s are time-domain on a noisy host, so 2x swings
    # flag (the hard sub-linear guard also lives in the bench)
    check(
        "summary_delta_shrink",
        result.get("summary_delta_shrink"),
        prev.get("summary_delta_shrink"),
        "lower",
        0.10,
    )
    for key in (
        "federation_tree_16x4096_p50_ms",
        "federation_tree_64x1024_p50_ms",
        "summary_delta_decode_ms",
    ):
        check(key, result.get(key), prev.get(key), "higher", 1.0)
    p_now, p_prev = result.get("probes", {}), prev.get("probes", {})
    for key in ("matmul_bf16_tflops", "hbm_stream_gbps", "hbm_copy_gbps"):
        check(key, p_now.get(key), p_prev.get(key), "lower", 0.05)
    # the overload fast paths (ISSUE 3): single-digit-ms numbers on a
    # noisy shared host, so only a 2x inflation flags — that's the size
    # of accidentally dragging a lock wait or executor hop into a shed
    for key in ("shed_503_p50_ms", "stale_frame_p50_ms"):
        check(key, result.get(key), prev.get(key), "higher", 1.0)
    # the broadcast plane (ISSUE 6): per-cohort compose cost is one
    # executor hop of deterministic work, but time-domain on a noisy
    # host, so a 2x inflation flags — the size of per-subscriber work
    # leaking back into the seal path (the hard ≤0.1 ms/client marginal
    # guard lives inside bench_sse_subscribers itself)
    for key in (
        "sse_subscribers_256_cohort_ms_per_tick",
        "sse_subscribers_1024_cohort_ms_per_tick",
    ):
        check(key, result.get(key), prev.get(key), "higher", 1.0)
    # the trend store (ISSUE 5): compression is deterministic (tight 10%
    # band); throughput/latency are time-domain on a noisy host, so only
    # a 2x swing flags — the size of a lost fast path, not scheduler jitter
    check(
        "tsdb_compression_ratio",
        result.get("tsdb_compression_ratio"),
        prev.get("tsdb_compression_ratio"),
        "lower",
        0.10,
    )
    check(
        "tsdb_ingest_points_per_s",
        result.get("tsdb_ingest_points_per_s"),
        prev.get("tsdb_ingest_points_per_s"),
        "lower",
        0.50,
    )
    # the native-columnar wire tier (ISSUE 10): binary delta size is
    # deterministic (10% band — growth means the quantized encoding
    # degraded); the seal-side encode cost and the native ingest rate
    # are time-domain on a noisy host, so 2x swings flag
    check(
        "scale_4096_binary_delta_bytes",
        result.get("scale_4096_binary_delta_bytes"),
        prev.get("scale_4096_binary_delta_bytes"),
        "higher",
        0.10,
    )
    check(
        "scale_4096_bin_seal_ms",
        result.get("scale_4096_bin_seal_ms"),
        prev.get("scale_4096_bin_seal_ms"),
        "higher",
        1.0,
    )
    # the columnar full frame + shm bus fan-out (ISSUE 11): frame bytes
    # are deterministic (10% band — growth means the template/cfull
    # encoding degraded); the fan-out CPU and flat-ratio are time-domain
    # on a noisy host, so 2x swings flag (the hard ≤300KB and ≤2.5x
    # flat guards live inside bench_scale / bench_bus_fanout themselves)
    check(
        "scale_4096_full_frame_bytes",
        result.get("scale_4096_full_frame_bytes"),
        prev.get("scale_4096_full_frame_bytes"),
        "higher",
        0.10,
    )
    check(
        "bus_fanout_cpu_ms_per_seal_4w",
        result.get("bus_fanout_cpu_ms_per_seal_4w"),
        prev.get("bus_fanout_cpu_ms_per_seal_4w"),
        "higher",
        1.0,
    )
    check(
        "bus_fanout_flat_ratio",
        result.get("bus_fanout_flat_ratio"),
        prev.get("bus_fanout_flat_ratio"),
        "higher",
        1.0,
    )
    # the edge delivery tier (ISSUE 16): per-tick CPU is time-domain on
    # a noisy host — 2x swings flag; the flat ratios are the structural
    # quantities (the hard ≤1.3x guards live inside bench_edge_fanout)
    check(
        "edge_fanout_cpu_ms_per_tick_4e",
        result.get("edge_fanout_cpu_ms_per_tick_4e"),
        prev.get("edge_fanout_cpu_ms_per_tick_4e"),
        "higher",
        1.0,
    )
    check(
        "edge_fanout_cpu_flat_ratio",
        result.get("edge_fanout_cpu_flat_ratio"),
        prev.get("edge_fanout_cpu_flat_ratio"),
        "higher",
        1.0,
    )
    check(
        "edge_fanout_egress_flat_ratio",
        result.get("edge_fanout_egress_flat_ratio"),
        prev.get("edge_fanout_egress_flat_ratio"),
        "higher",
        1.0,
    )
    check(
        "tsdb_ingest_mpoints_per_s",
        result.get("tsdb_ingest_mpoints_per_s"),
        prev.get("tsdb_ingest_mpoints_per_s"),
        "lower",
        0.50,
    )
    check(
        "tsdb_range_p50_ms",
        result.get("tsdb_range_p50_ms"),
        prev.get("tsdb_range_p50_ms"),
        "higher",
        1.0,
    )
    # anomaly scoring hook (ISSUE 12): time-domain per-tick numbers on a
    # noisy host — 2x swings flag, the size of a lost vectorized path
    # (the hard <10%-of-frame-budget bar lives inside
    # bench_anomaly_scoring itself)
    for key in (
        "anomaly_score_1024_p50_ms",
        "anomaly_score_4096_p50_ms",
    ):
        check(key, result.get(key), prev.get(key), "higher", 1.0)
    # federation fan-in (ISSUE 9): time-domain whole-pipeline numbers on
    # a noisy host — 2x swings flag (the size of a lost batch-union or
    # summary-decode fast path, not scheduler jitter)
    for key in (
        "federation_fanin_8_p50_ms",
        "federation_fanin_16_p50_ms",
    ):
        check(key, result.get(key), prev.get(key), "higher", 1.0)
    # the analytics query plane (ISSUE 13): sketch-vs-raw speedup is
    # ratio-domain (halving means the sketch read path degraded); the
    # p50s are time-domain on a noisy host — 2x swings flag (the hard
    # ≥10x and accuracy-bound bars live inside bench_range_quantiles)
    check(
        "range_quantile_speedup",
        result.get("range_quantile_speedup"),
        prev.get("range_quantile_speedup"),
        "lower",
        0.50,
    )
    for key in (
        "range_quantile_sketch_p50_ms",
        "federated_range_fanin_16_p50_ms",
    ):
        check(key, result.get(key), prev.get(key), "higher", 1.0)
    # the cold archive tier (ISSUE 18): the sketch-index read and the
    # compaction rate are time-domain on a noisy host — 2x swings flag
    # (the hard <1s gate and the zero-raw-decode proof live inside
    # bench_cold_range itself)
    check(
        "cold_range_90d_p50_ms",
        result.get("cold_range_90d_p50_ms"),
        prev.get("cold_range_90d_p50_ms"),
        "higher",
        1.0,
    )
    check(
        "cold_compact_mb_per_s",
        result.get("cold_compact_mb_per_s"),
        prev.get("cold_compact_mb_per_s"),
        "lower",
        0.50,
    )
    # durability tier (ISSUE 8): snapshot duration and follower replay
    # are time-domain on a noisy host — 2x swings flag (the hard
    # near-zero ingest-stall guard lives inside bench_snapshot itself)
    check(
        "snapshot_ms",
        result.get("snapshot_ms"),
        prev.get("snapshot_ms"),
        "higher",
        1.0,
    )
    check(
        "follower_catchup_points_per_s",
        result.get("follower_catchup_points_per_s"),
        prev.get("follower_catchup_points_per_s"),
        "lower",
        0.50,
    )
    # headline p50: compare in MACHINE-RELATIVE terms when both records
    # carry the CPU reference — this host's effective clock swings ±30%
    # with neighbors, and a level shift is not a code regression
    now_p50, prev_p50 = result.get("value"), prev.get("value")
    # prefer the frame-shaped JSON reference (tracks the contention that
    # actually slows the frame path; see cpu_reference_json_ms) over the
    # matmul one; fall back so older records stay comparable
    now_ref, prev_ref = (
        result.get("cpu_ref_json_ms"),
        prev.get("cpu_ref_json_ms"),
    )
    if not (
        isinstance(now_ref, (int, float)) and isinstance(prev_ref, (int, float))
    ):
        now_ref, prev_ref = result.get("cpu_ref_ms"), prev.get("cpu_ref_ms")
    if (
        isinstance(now_p50, (int, float))
        and isinstance(prev_p50, (int, float))
        and isinstance(now_ref, (int, float))
        and isinstance(prev_ref, (int, float))
        and now_ref > 0
        and prev_ref > 0
    ):
        check(
            "value_per_cpu_ref",
            now_p50 / now_ref,
            prev_p50 / prev_ref,
            "higher",
            0.20,
        )
    else:
        check("value", now_p50, prev_p50, "higher", 0.20)
    return os.path.basename(files[-1]), out


def main() -> None:
    t0 = time.time()
    dash = bench_dashboard()
    multi = bench_multislice()
    torus3d = bench_3d_torus()
    links = bench_link_detail()
    scale1k = bench_scale(1024)
    try:
        scale4k = bench_scale(
            4096,
            p50_budget_ms=SCALE_4096_P50_BUDGET_MS,
            binary_floor_bytes=R05_JSON_DELTA_BYTES // 3,
            full_frame_budget_bytes=SCALE_4096_FULL_FRAME_BUDGET_BYTES,
        )
    except AssertionError:
        # the 20ms gate is a hard bar, but one scheduler burst on a
        # shared host must not cost the whole bench record — a single
        # retry re-measures; a genuine regression fails both runs
        scale4k = bench_scale(
            4096,
            p50_budget_ms=SCALE_4096_P50_BUDGET_MS,
            binary_floor_bytes=R05_JSON_DELTA_BYTES // 3,
            full_frame_budget_bytes=SCALE_4096_FULL_FRAME_BUDGET_BYTES,
        )
    bus_fanout = bench_bus_fanout()
    edge_fanout = bench_edge_fanout()
    sse_subs = bench_sse_subscribers()
    shed = bench_shed_latency()
    tsdb = bench_tsdb()
    snapshot = bench_snapshot()
    federation = bench_federation()
    federation_tree = bench_federation_tree()
    anomaly_scoring = bench_anomaly_scoring()
    range_quantiles = bench_range_quantiles()
    federated_range = bench_federated_range()
    cold_range = bench_cold_range()
    probes = bench_probes()
    p50 = dash["p50_s"]
    result = {
        "metric": f"scrape_to_render_p50_at_{N_CHIPS}_chips",
        "value": round(p50 * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round(BUDGET_S / p50, 1),
        "p95_ms": round(dash["p95_s"] * 1e3, 2),
        "frames": N_FRAMES,
        "budget_s": BUDGET_S,
        "sse_full_frame_bytes": dash["sse_bytes"],
        "sse_delta_bytes": dash["sse_delta_bytes"],
        "sse_delta_gzip_bytes": dash["sse_delta_gzip_bytes"],
        "frame_gzip_bytes": dash["frame_gzip_bytes"],
        "multislice_2x256_p50_ms": round(multi["p50_s"] * 1e3, 2),
        "torus3d_v4_4x4x8_p50_ms": round(torus3d["p50_s"] * 1e3, 2),
        "torus3d_grid": torus3d["grid"],
        "link_detail_256_p50_ms": round(links["p50_s"] * 1e3, 2),
        "scale_1024_p50_ms": round(scale1k["p50_s"] * 1e3, 2),
        "scale_1024_sse_delta_bytes": scale1k["sse_delta_bytes"],
        "scale_1024_binary_delta_bytes": scale1k["binary_delta_bytes"],
        "scale_1024_rss_mb": scale1k["rss_mb"],
        "scale_4096_p50_ms": round(scale4k["p50_s"] * 1e3, 2),
        "scale_4096_sse_delta_bytes": scale4k["sse_delta_bytes"],
        "scale_4096_binary_delta_bytes": scale4k["binary_delta_bytes"],
        "scale_4096_bin_seal_ms": scale4k["bin_seal_ms"],
        "scale_4096_full_frame_bytes": scale4k["full_frame_bytes"],
        "scale_4096_full_frame_tpl_bytes": scale4k["full_frame_tpl_bytes"],
        "scale_4096_full_frame_cfull_bytes": scale4k[
            "full_frame_cfull_bytes"
        ],
        "scale_4096_full_frame_json_bytes": scale4k[
            "full_frame_json_bytes"
        ],
        "scale_4096_full_frame_encode_ms": scale4k["full_frame_encode_ms"],
        "scale_4096_rss_mb": scale4k["rss_mb"],
        "scale_4096_rss_growth_mb": scale4k["rss_growth_mb"],
        **bus_fanout,
        **edge_fanout,
        **sse_subs,
        **shed,
        **tsdb,
        **snapshot,
        **federation,
        **federation_tree,
        **anomaly_scoring,
        **range_quantiles,
        **federated_range,
        **cold_range,
        "probes": probes,
        "cpu_ref_ms": cpu_reference_ms(),
        "cpu_ref_json_ms": cpu_reference_json_ms(),
        "bench_wall_s": round(time.time() - t0, 1),
    }
    vs_file, regressions = find_regressions(result)
    if vs_file is not None:
        result["vs_prev"] = vs_file
        result["regressions"] = regressions
    result["bench_wall_s"] = round(time.time() - t0, 1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
